#!/usr/bin/env python
"""Headline benchmark: dense-matmul GFLOPS/chip driven through /v1/execute.

Measures the BASELINE.json north-star metric — the benchmark-numpy dense
matmul payload submitted through the service's real execution path (the
sandbox executor with the TPU runtime shim), reported as GFLOPS on the
attached chip. ``vs_baseline`` compares against the same payload on the host
CPU path (the reference's only execution substrate; BASELINE.md "the
reference's CPU path is the comparison baseline").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "GFLOPS", "vs_baseline": N,
     "latency_warm_p50_ms": N | null, "cpu_baseline_gflops": N,
     "serving": {...} when the continuous-batching stack ran through the
     service path (tokens/sec, TTFT p50/p95, inter-token latency, and a
     measured instrumentation on/off overhead — models/serving_bench.py),
     "hardware_evidence": [...]}

Extra detail lines go to stderr.

Capture-on-healthy (round-3 lesson): the TPU tunnel flips healthy<->wedged
within sessions, so the probe is PATIENT — re-probing on a cadence up to
``BCI_BENCH_TPU_PATIENCE_S`` (default 20 min) and measuring the moment a
probe succeeds — and every successful hardware measurement (from this
script and from scripts/bench-*.py / validate-*.py) is appended to the
``TPU_EVIDENCE.jsonl`` ledger, whose latest entries ride along in this
output's ``hardware_evidence`` field. A SIGTERM mid-patience still emits
the complete fallback artifact.

Ordering and guards (round-1 lesson, BENCH_r01.json rc=1): the TPU
measurement — the number this benchmark exists to produce — runs FIRST and
nothing that happens to the auxiliary measurements can take it down. The CPU
baseline runs second, try/except-guarded, in a process env scrubbed of
accelerator-tunnel plugin vars (PALLAS_*/AXON_* hook jax backend init even
under JAX_PLATFORMS=cpu and block on a single-client tunnel) with the reroute
opted out via the *request env* (not in-script — numpy may already be proxied
by the time user code runs). If the live baseline fails anyway, a recorded
baseline keeps ``vs_baseline`` meaningful and is flagged on stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
SHIM_DIR = REPO / "bee_code_interpreter_tpu" / "runtime" / "shim"

# Capture-on-healthy (VERDICT r3 next-round #1): the tunnel to the chip
# provably flips healthy<->wedged within a session, so a single 75 s probe +
# one attempt is mis-sized patience. When the first probe fails, bench.py now
# keeps re-probing on a cadence — bounded, out-of-process — up to this
# ceiling, and runs the payload the moment a probe succeeds. Every probe and
# attempt lands in the output JSON. A SIGTERM/SIGINT during the wait still
# emits a complete fallback artifact (see _install_kill_safe_emit), so a
# driver timeout can shorten the patience but never produce an empty record.
TPU_PATIENCE_S = float(os.environ.get("BCI_BENCH_TPU_PATIENCE_S", "1200"))
# Gentle cadence (round-4 discovery, scripts/tpu-oneshot.py): killed probe
# clients appear to HOLD the tunnel wedged — a 45-60 s probe storm prevents
# the very recovery it is waiting for. 180 s gives the tunnel quiet time
# while still catching a window inside the default patience.
TPU_PROBE_INTERVAL_S = float(os.environ.get("BCI_BENCH_TPU_PROBE_INTERVAL_S", "180"))

N = 32768
ITERS = 16

# The measured payload: a bf16 matmul chain under jit, the shape of work the
# MXU exists for. Chained with a data dependency (no loop hoisting), one
# device->host readback at the end. Written the way a sandbox user writes JAX.
# n=32768 keeps each matmul MXU-bound long enough to amortize loop/dispatch
# overhead (measured 186 TFLOPS = 94% of v5e bf16 peak vs 147 at n=8192); the
# one-time 1/128 pre-scale keeps the chain's magnitudes roughly stable without
# paying a per-iteration epilogue.
TPU_PAYLOAD = f"""
import time
import jax, jax.numpy as jnp
from jax import lax

n, iters = {N}, {ITERS}
on_tpu = jax.devices()[0].platform == "tpu"
if not on_tpu:
    n, iters = 1024, 4  # no accelerator: validate mechanics only
a = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype=jnp.bfloat16)

@jax.jit
def chain(a):
    a = a * jnp.bfloat16(1 / 128)
    def body(i, x):
        return a @ x
    return lax.fori_loop(0, iters, body, a).sum()

float(chain(a))  # compile + warm
best = float("inf")
for _ in range(3):
    t0 = time.time()
    float(chain(a))
    best = min(best, time.time() - t0)
# second field: 1 iff the payload actually ran on a TPU — the harness must
# never headline a CPU-mechanics run as the per-chip number
print(f"RESULT_GFLOPS {{2 * n**3 * iters / best / 1e9:.1f}} {{1 if on_tpu else 0}}")
"""

# Host-CPU baseline: the same kernel as the TPU chain — one-time 1/128
# pre-scale, then a pure data-dependent matmul chain with a single readback —
# through plain numpy (f32; numpy has no bf16), sized down (self-timed wall
# clock, as the reference's own benchmark payload does). n=2048 is enough to
# saturate the host BLAS; anything larger just risks the driver's clock.
CPU_PAYLOAD = """
import time
import numpy as np

n, iters = 2048, 8
a = np.random.rand(n, n).astype(np.float32) * np.float32(1 / 128)
x = a
t0 = time.time()
for _ in range(iters):
    x = a @ x
s = float(x.sum())
dt = time.time() - t0
print(f"RESULT_GFLOPS {2 * n**3 * iters / dt / 1e9:.1f}")
"""

# Live-CPU-baseline fallback: the same payload measured out-of-band on this
# machine class (round-1 verification run: 120 GFLOPS through the identical
# LocalCodeExecutor path). Used only if the live baseline fails; stderr says so.
RECORDED_CPU_GFLOPS = 120.0

LATENCY_PAYLOAD = "print(21 * 2)"

#: HARD budget for the edge static-analysis gate on the warm path
#: (docs/analysis.md "Observability"): < 1 ms p50 added per execute, now
#: including the dataflow pass AND the accelerator cost classifier.
ANALYSIS_BUDGET_MS = 1.0


def check_analysis_budget(phases_p50: dict) -> None:
    """HARD budget, not a report: failing the whole latency phase is
    deliberate — a silently regressed gate would otherwise ride along
    inside a number nobody decomposes. Split out of measure_latency so
    tests/test_bench.py can pin the raise itself (the guard must keep
    firing as classifiers accrete on the gate)."""
    if phases_p50["analysis_ms"] >= ANALYSIS_BUDGET_MS:
        raise RuntimeError(
            f"analysis gate over budget: p50 {phases_p50['analysis_ms']:.3f} ms"
            f" >= {ANALYSIS_BUDGET_MS:g} ms — the static-analysis pass "
            "regressed the warm path"
        )

# Guarded extra evidence: the Pallas flash-attention kernel vs XLA's own
# fused attention, through the same execution path — so the kernel claims in
# BASELINE.md stop being builder-session-only. Timing by the
# (t_N - t_1)/(N-1) chain difference (utils/benchclock.py), which cancels
# the device->host readback RTT exactly. Shape and chain length are sized so
# the chain DOMINATES the ~70 ms tunnel RTT (flash ≈ 2.8 ms/call at the
# measured 99 TFLOPS → 31 extra calls ≈ 87 ms >> 1.2x guard margin) — a
# smaller shape would trip the sanity guard on every tunneled run and the
# field could never land. Cost on a healthy chip: 4 jit compiles (~25 s
# each worst-case) + ~4 s of timed chains, inside the 240 s budget.
FLASH_PAYLOAD = """
import time
import jax, jax.numpy as jnp
from jax import lax
from bee_code_interpreter_tpu.ops.flash_attention import flash_attention
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention
from bee_code_interpreter_tpu.utils.benchclock import chain_diff

B, H, L, D = 4, 16, 4096, 128
N = 32
q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, H, L, D), jnp.bfloat16)
           for i in range(3))

def chain(attn, length):
    @jax.jit
    def f(q, k, v):
        def body(c, _):
            return attn(c, k, v), None
        c, _ = lax.scan(body, q, None, length=length)
        return c.astype(jnp.float32).sum()
    return f

def per_call(attn, what):
    def best_of(f):
        float(f(q, k, v))
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(f(q, k, v))
            best = min(best, time.perf_counter() - t0)
        return best
    return chain_diff(best_of(chain(attn, N)), best_of(chain(attn, 1)), N, what)

t_fl = per_call(lambda q, k, v: flash_attention(q, k, v, True), "flash")
t_xl = per_call(
    lambda q, k, v: reference_attention(q, k, v, causal=True).astype(q.dtype),
    "xla",
)
flops = 2 * B * H * L * L * D  # causal: half of 4*B*H*L*L*D
print(f"RESULT_FLASH {flops / t_fl / 1e12:.2f} {flops / t_xl / 1e12:.2f}")
"""

# Serving phase through the service path (ROADMAP item 4: "a tokens/sec +
# TTFT trajectory alongside warm-execute p50"): a continuous-batching run
# on already-compiled programs, measured with the full observability stack
# attached AND bare, so every artifact carries tokens/sec, TTFT p50/p95,
# inter-token latency, and the MEASURED instrumentation overhead. The
# arithmetic lives in models/serving_bench.py (shared with the tier-1
# suite); arm-equality and pass-to-pass determinism are asserted inside.
# CPU-pinned: the point is a stable trajectory of the serving STACK, not a
# hardware number (that battery is scripts/bench-decode.py's ledger rows).
SERVING_PAYLOAD = """
import json
from bee_code_interpreter_tpu.models.serving_bench import run_serving_bench
print("RESULT_SERVING_JSON", json.dumps(run_serving_bench()))
"""


def probe_tpu(timeout_s: float = 75.0) -> dict:
    """Bounded out-of-process probe of the JAX accelerator backend.

    Two rounds of driver artifacts couldn't distinguish "chip absent" from
    "backend init hung" from "payload too slow" (VERDICT r2 weak #1); this
    records which. A hung tunnel hangs the subprocess, not the bench.
    """
    t0 = time.time()
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; ds = jax.devices(); "
                "print('PROBE', ds[0].platform, len(ds))",
            ],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        seconds = round(time.time() - t0, 1)
        for line in out.stdout.splitlines():
            if line.startswith("PROBE "):
                _, platform, count = line.split()
                return {
                    "ok": True,
                    "seconds": seconds,
                    "platform": platform,
                    "device_count": int(count),
                }
        return {
            "ok": False,
            "seconds": seconds,
            "error": f"probe exited {out.returncode} without a device line",
            "stderr_tail": out.stderr[-400:],
        }
    except subprocess.TimeoutExpired as e:
        return {
            "ok": False,
            "seconds": round(time.time() - t0, 1),
            "error": f"jax.devices() hung past {timeout_s:.0f}s (wedged TPU tunnel)",
            "stderr_tail": ((e.stderr or b"").decode("utf-8", "replace"))[-400:],
        }


class PayloadError(RuntimeError):
    """Payload failure carrying the sandbox stderr for the bench artifact."""

    def __init__(self, msg: str, stderr: str = "") -> None:
        super().__init__(msg)
        self.stderr = stderr


async def run_payload(
    source: str, env: dict[str, str], timeout_s: float,
    marker: str = "RESULT_GFLOPS",
) -> float:
    values = await run_payload_values(source, env, timeout_s, marker)
    return values[0]


async def run_payload_values(
    source: str, env: dict[str, str], timeout_s: float, marker: str
) -> list[float]:
    """Execute through the service path; return the floats following
    ``marker`` on the payload's result line."""
    return (await run_payload_multi(source, env, timeout_s, (marker,)))[marker]


async def _run_payload_result(source: str, env: dict[str, str], timeout_s: float):
    """One execution through the service path — the scaffold the marker
    parsers below share; raises PayloadError (stderr attached) on a
    nonzero exit."""
    from bee_code_interpreter_tpu.services.local_code_executor import (
        LocalCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = tempfile.mkdtemp(prefix="bench-")
    executor = LocalCodeExecutor(
        storage=Storage(Path(tmp) / "objects"),
        workspace_root=Path(tmp) / "ws",
        disable_dep_install=True,
        execution_timeout_s=timeout_s,
        shim_dir=SHIM_DIR,
    )
    result = await executor.execute(source, env=env)
    if result.exit_code != 0:
        print(result.stderr, file=sys.stderr)
        raise PayloadError(
            f"payload failed (exit {result.exit_code})", stderr=result.stderr
        )
    return result


async def run_payload_multi(
    source: str, env: dict[str, str], timeout_s: float,
    markers: tuple[str, ...],
) -> dict[str, list[float]]:
    """Execute ONCE through the service path; return the floats following
    each ``marker`` line (one executor run can carry several measurements —
    scripts/bench-mfu.py's train + decode share a payload)."""
    result = await _run_payload_result(source, env, timeout_s)
    out: dict[str, list[float]] = {}
    for line in result.stdout.splitlines():
        for marker in markers:
            if line.startswith(marker):
                out[marker] = [float(tok) for tok in line.split()[1:]]
    missing = [m for m in markers if m not in out]
    if missing:
        raise PayloadError(
            f"no {missing} in stdout: {result.stdout!r}"
        )
    return out


async def run_payload_json(
    source: str, env: dict[str, str], timeout_s: float, marker: str
) -> dict:
    """Execute through the service path; return the JSON object following
    ``marker`` on the payload's result line (structured measurements — the
    serving phase reports a whole dict, not a float tuple)."""
    result = await _run_payload_result(source, env, timeout_s)
    for line in result.stdout.splitlines():
        if line.startswith(marker):
            return json.loads(line[len(marker):])
    raise PayloadError(f"no {marker} in stdout: {result.stdout!r}")


def scrub_tunnel_vars() -> None:
    """Drop accelerator-tunnel plugin vars from THIS process (inherited by the
    executor's TPU_PASSTHROUGH_PREFIXES) so CPU-pinned payloads cannot be
    hijacked into a blocking TPU backend init. Called only after the TPU
    measurement — which needs those very vars — has completed."""
    from bee_code_interpreter_tpu.utils.envscrub import scrub_tunnel_plugin_vars

    scrub_tunnel_plugin_vars()


def ensure_native_binary() -> Path | None:
    """Build the C++ executor if needed — synchronously, OUTSIDE any event
    loop (a blocking subprocess inside a coroutine would stall the loop and
    defeat the asyncio.wait_for guard around the latency measurement)."""
    binary = REPO / "executor" / "build" / "executor-server"
    if binary.exists():
        return binary
    try:
        build = subprocess.run(
            ["make", "-C", str(REPO / "executor"), "-s"],
            capture_output=True,
            timeout=180,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"latency: executor build failed ({e})", file=sys.stderr)
        return None
    if build.returncode != 0 or not binary.exists():
        print("latency: no native executor binary", file=sys.stderr)
        return None
    return binary


async def measure_warm_latency_p50_ms(
    binary: Path, n: int = 20
) -> tuple[float, dict] | None:
    """p50 of a trivial execute through the warm native-executor pool, plus a
    per-phase p50 breakdown (analysis / acquire / upload / POST / in-sandbox /
    overhead / download) so a regressed number names its phase instead of
    inviting guesses about host load (VERDICT r2 weak #2). The edge
    static-analysis gate (docs/analysis.md) runs before each execute exactly
    as the API edge does, so the BENCH trajectory records what the gate
    COSTS the warm path, not just what it saves (< 1ms p50 is the budget).
    scripts/measure-latency.py is the full percentile harness."""
    from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="bench-lat-"))
    config = Config(
        file_storage_path=str(tmp / "objects"),
        local_workspace_root=str(tmp / "ws"),
        executor_pod_queue_target_length=4,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=Storage(tmp / "objects"), config=config, binary=binary
    )
    analyzer = WorkloadAnalyzer()  # default (empty) policy: the gate's floor cost
    # The capacity tracker rides the fleet journal exactly as the
    # composition root wires it (docs/autoscaling.md), so this p50 INCLUDES
    # the demand-sampling cost — the <5% acceptance budget is measured on
    # every artifact, not asserted blind.
    from bee_code_interpreter_tpu.observability import DemandTracker

    executor.journal.add_sink(DemandTracker().on_fleet_event)
    try:
        await executor.fill_sandbox_queue()
        samples: list[float] = []
        phase_samples: list[dict] = []
        for i in range(n):
            if i:
                # Pace requests: this measures warm-pool REQUEST latency, not
                # saturated throughput (back-to-back requests outrun the
                # refill pipeline and every pop hits a sandbox whose warm
                # interpreter is still preloading — that's a throughput
                # ceiling, a different metric). The sleep is excluded from
                # the samples.
                await asyncio.sleep(0.35)
            t0 = time.perf_counter()
            # The edge gate runs first, exactly as /v1/execute does; its
            # cost is inside the sample AND reported as its own phase.
            verdict = analyzer.analyze(LATENCY_PAYLOAD)
            analysis_ms = (time.perf_counter() - t0) * 1000.0
            if verdict.syntax_error is not None or verdict.denials:
                raise RuntimeError("latency payload refused by the gate?!")
            result = await executor.execute(LATENCY_PAYLOAD)
            if result.stdout != "42\n":
                raise RuntimeError(f"latency payload failed: {result.stderr}")
            samples.append(time.perf_counter() - t0)
            phase_samples.append(
                {**executor.last_execute_phases, "analysis_ms": analysis_ms}
            )
        phases_p50 = {
            key: round(
                statistics.median(
                    float(p.get(key, 0.0)) for p in phase_samples
                ),
                1 if key != "analysis_ms" else 3,
            )
            for key in (
                "analysis_ms",
                "acquire_ms",
                "upload_ms",
                "post_execute_ms",
                "sandbox_ms",
                "overhead_ms",
                "download_ms",
            )
        }
        phases_p50["warm_pop_rate"] = round(
            sum(1 for p in phase_samples if p.get("warm_pop")) / len(phase_samples),
            2,
        )
        check_analysis_budget(phases_p50)
        return statistics.median(samples) * 1000, phases_p50
    finally:
        executor.shutdown()


async def measure_surge(binary: Path) -> dict | None:
    """The `surge` phase (docs/autoscaling.md): a load step against the
    native warm pool, A/B with the predictive autoscaler in ``act`` vs
    ``off``. Reports time-to-absorb (seconds from the step until a whole
    burst pops warm again, warm_pop_ratio >= 0.95) and how many requests
    the admission gate shed while the pool was cold — the two numbers the
    capacity loop exists to improve. Starts the surge trajectory next to
    warm p50 and tokens/sec in the BENCH artifact."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.observability import DemandTracker, Forecaster
    from bee_code_interpreter_tpu.resilience import (
        AdmissionController,
        AdmissionRejected,
        PoolAutoscaler,
        PoolSupervisor,
    )
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    # Burst/pace sized for a 1-core bench box: the native refill pipeline
    # produces ~5 sandboxes/s there (serialized CPU-bound spawns), so the
    # sustained demand (~2.7/s) must sit under it or even a perfectly
    # scaled pool can never catch up and the A/B measures host load.
    BURST, MAX_ROUNDS, PACE_S = 4, 8, 1.5

    async def arm(mode: str) -> dict:
        tmp = Path(tempfile.mkdtemp(prefix=f"bench-surge-{mode}-"))
        config = Config(
            file_storage_path=str(tmp / "objects"),
            local_workspace_root=str(tmp / "ws"),
            executor_pod_queue_target_length=2,
            disable_dep_install=True,
        )
        executor = NativeProcessCodeExecutor(
            storage=Storage(tmp / "objects"), config=config, binary=binary
        )
        demand = DemandTracker()
        executor.journal.add_sink(demand.on_fleet_event)
        forecaster = Forecaster(demand)
        admission = AdmissionController(
            max_in_flight=8, max_queue=0, retry_after_s=0.1, demand=demand
        )
        autoscaler = PoolAutoscaler(
            executor, forecaster, demand,
            mode=mode, min_size=1, max_size=8, idle_s=60.0, cooldown_s=0.0,
            base_target=2,
        )
        supervisor = PoolSupervisor(
            executor, interval_s=0.2, autoscaler=autoscaler
        )

        async def one_request() -> bool:
            try:
                async with admission.admit():
                    result = await executor.execute(LATENCY_PAYLOAD)
                    return result.exit_code == 0
            except AdmissionRejected:
                return False

        def assigned_counts() -> tuple[int, int]:
            warm = cold = 0
            for e in executor.journal.events():
                if e["state"] == "assigned":
                    if e.get("reason") == "warm_pop":
                        warm += 1
                    else:
                        cold += 1
            return warm, cold

        try:
            await executor.fill_sandbox_queue()
            supervisor.start()
            for _ in range(3):  # steady trickle: baseline demand + spawns
                await one_request()
                await asyncio.sleep(0.3)
            t_step = time.perf_counter()
            absorb_s: float | None = None
            for _ in range(MAX_ROUNDS):
                warm0, cold0 = assigned_counts()
                await asyncio.gather(*(one_request() for _ in range(BURST)))
                warm1, cold1 = assigned_counts()
                popped = (warm1 - warm0) + (cold1 - cold0)
                ratio = (warm1 - warm0) / popped if popped else 1.0
                if absorb_s is None and ratio >= 0.95:
                    absorb_s = time.perf_counter() - t_step
                    break
                await asyncio.sleep(PACE_S)
            return {
                "absorb_s": round(absorb_s, 2) if absorb_s is not None else None,
                "sheds": demand.sheds_total,
                "pool_target_final": executor.pool_target,
                "decisions": len(autoscaler.decisions()),
            }
        finally:
            await supervisor.stop()
            # Let in-flight refills land before teardown: a spawn racing
            # aclose() would just die noisily against the closed pool.
            for _ in range(100):
                if executor.pool_spawning_count == 0:
                    break
                await asyncio.sleep(0.05)
            await executor.aclose()

    on = await arm("act")
    off = await arm("off")
    return {
        "burst": BURST,
        "pace_s": PACE_S,
        "autoscaler_on": on,
        "autoscaler_off": off,
    }


async def measure_fairness(binary: Path) -> dict | None:
    """The `fairness` phase (docs/tenancy.md): victim-tenant p50 with and
    without an abusive tenant flooding 100x its rate quota through the
    tenant-aware admission gate over the native warm pool. The isolation
    budget is < 10% victim degradation at 100x abuse — published as a
    measured number on every artifact, not asserted blind."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.resilience import (
        AdmissionController,
        AdmissionRejected,
    )
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage
    from bee_code_interpreter_tpu.tenancy import TenantRegistry, parse_tenants

    tmp = Path(tempfile.mkdtemp(prefix="bench-fair-"))
    config = Config(
        file_storage_path=str(tmp / "objects"),
        local_workspace_root=str(tmp / "ws"),
        executor_pod_queue_target_length=3,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=Storage(tmp / "objects"), config=config, binary=binary
    )
    registry = TenantRegistry(
        parse_tenants("abuser:weight=1:rps=2:burst=2,victim:weight=4")
    )
    admission = AdmissionController(
        max_in_flight=4, max_queue=8, retry_after_s=0.1, tenancy=registry
    )
    N_ABUSE = 200  # 100x the abuser's burst-2 token bucket

    async def victim_request() -> float:
        t0 = time.perf_counter()
        async with admission.admit(tenant=registry.resolve("victim")):
            result = await executor.execute(LATENCY_PAYLOAD)
            if result.exit_code != 0:
                raise RuntimeError(f"victim payload failed: {result.stderr}")
        return time.perf_counter() - t0

    async def abuser_request() -> None:
        try:
            async with admission.admit(tenant=registry.resolve("abuser")):
                await executor.execute(LATENCY_PAYLOAD)
        except AdmissionRejected:
            pass  # the quota's verdict — exactly the isolation mechanism

    try:
        await executor.fill_sandbox_queue()
        solo: list[float] = []
        for _ in range(12):
            solo.append(await victim_request())
            await asyncio.sleep(0.25)
        flood = [
            asyncio.ensure_future(abuser_request()) for _ in range(N_ABUSE)
        ]
        under: list[float] = []
        for _ in range(12):
            under.append(await victim_request())
            await asyncio.sleep(0.25)
        await asyncio.gather(*flood)
        p50_solo = statistics.median(solo) * 1000.0
        p50_abuse = statistics.median(under) * 1000.0
        lanes = admission.tenant_snapshot()
        return {
            "victim_p50_solo_ms": round(p50_solo, 1),
            "victim_p50_under_abuse_ms": round(p50_abuse, 1),
            "degradation_pct": round((p50_abuse / p50_solo - 1.0) * 100.0, 1),
            "budget_ok": p50_abuse <= p50_solo * 1.10,  # the < 10% budget
            "abuse_requests": N_ABUSE,
            "abuser_sheds": sum(lanes["abuser"]["sheds"].values()),
            "abuser_admitted": lanes["abuser"]["admitted"],
            "victim_sheds": sum(lanes["victim"]["sheds"].values()),
        }
    finally:
        await executor.aclose()


async def measure_router(binary: Path) -> dict | None:
    """The `router` phase (docs/fleet.md): p50 of the SAME warm execute
    direct-to-replica vs through the fleet-router edge — the routing tax,
    budgeted < 2 ms added p50 — plus the consistent-hash warm-affinity hit
    rate on repeat-client traffic (>= 90% expected: repeat keys must keep
    landing where their snapshot chain is warm). Two complete replicas
    (real HTTP edge over the native pool) share one snapshot root; samples
    alternate arms so host drift cancels."""
    import socket
    import statistics as stats

    import httpx
    from aiohttp import web

    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.fleet import FleetRouter, create_router_app
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import (
        SharedDirectoryBackend,
        Storage,
    )

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ROUNDS, KEYS = 24, 4
    tmp = Path(tempfile.mkdtemp(prefix="bench-router-"))
    shared_root = tmp / "objects"
    replicas: list[tuple] = []
    router = None
    router_runner = None
    client = None
    try:
        for i in range(2):
            storage = Storage(backend=SharedDirectoryBackend(shared_root))
            config = Config(
                file_storage_path=str(shared_root),
                local_workspace_root=str(tmp / f"ws-{i}"),
                executor_pod_queue_target_length=2,
                disable_dep_install=True,
            )
            executor = NativeProcessCodeExecutor(
                storage=storage, config=config, binary=binary
            )
            await executor.fill_sandbox_queue()
            app = create_http_server(
                code_executor=executor,
                custom_tool_executor=CustomToolExecutor(code_executor=executor),
            )
            runner = web.AppRunner(app)
            await runner.setup()
            port = free_port()
            await web.TCPSite(runner, "127.0.0.1", port).start()
            replicas.append((executor, runner, f"http://127.0.0.1:{port}"))
        # No background refresh: the view is refreshed manually while the
        # fleet is idle, so this LATENCY bench can't trip the overload-spill
        # path by having a refresh catch the (sequentially driven) owner
        # mid-request — spill behavior is chaos/tier-1 territory
        # (tests/test_fleet_router.py), the bench measures tax + affinity.
        router = FleetRouter(
            [(f"r{i}", r[2]) for i, r in enumerate(replicas)],
            refresh_interval_s=30.0,
        )
        router_runner = web.AppRunner(create_router_app(router))
        await router_runner.setup()
        router_port = free_port()
        await web.TCPSite(router_runner, "127.0.0.1", router_port).start()
        router_url = f"http://127.0.0.1:{router_port}"
        await router.refresh_once()

        seed_storage = Storage(backend=SharedDirectoryBackend(shared_root))
        seeds = []
        for i in range(KEYS):
            object_id = await seed_storage.write(f"router-chain-{i}".encode())
            seeds.append({"/workspace/seed.txt": object_id})

        client = httpx.AsyncClient(timeout=30.0)

        async def timed(url: str, files: dict) -> float:
            t0 = time.perf_counter()
            response = await client.post(
                f"{url}/v1/execute",
                json={"source_code": "print('ok')", "files": files},
            )
            if response.status_code != 200 or response.json()["exit_code"] != 0:
                raise RuntimeError(f"router bench execute failed: {response.text}")
            return (time.perf_counter() - t0) * 1000.0

        from bee_code_interpreter_tpu.fleet import affinity_key

        def owner_url(files: dict) -> str:
            # "direct-to-replica" is the ideal client that already knows
            # where its snapshot chain is warm: the key's ring owner — the
            # same replica the router should pick, so both arms measure the
            # same replica in the same state and the difference IS the tax.
            owner = router.ring.owner(affinity_key(files))
            return dict(
                (f"r{i}", r[2]) for i, r in enumerate(replicas)
            )[owner]

        # PACE_S between requests lets the pool refill land, so every
        # sample pops warm: a random cold spawn is tens of ms of noise
        # against a single-digit-ms tax.
        PACE_S = 0.15

        async def timed_paced(url: str, files: dict) -> float:
            sample = await timed(url, files)
            await asyncio.sleep(PACE_S)
            return sample

        # Warm both arms (pool probe + first-touch costs land here).
        for files in seeds:
            await timed_paced(owner_url(files), files)
            await timed_paced(router_url, files)
        await router.refresh_once()  # idle fleet: placement view settles
        direct_ms: list[float] = []
        routed_ms: list[float] = []
        for i in range(ROUNDS):
            files = seeds[i % KEYS]
            # alternate arm ORDER per round so drift cancels
            if i % 2 == 0:
                direct_ms.append(await timed_paced(owner_url(files), files))
                routed_ms.append(await timed_paced(router_url, files))
            else:
                routed_ms.append(await timed_paced(router_url, files))
                direct_ms.append(await timed_paced(owner_url(files), files))
        keyed = (
            router.affinity_totals["warm"] + router.affinity_totals["spill"]
        )
        direct_p50 = stats.median(direct_ms)
        router_p50 = stats.median(routed_ms)
        # The tax is the MEDIAN OF PAIRED same-key differences, not the
        # difference of medians: pairing cancels per-key and drift effects,
        # and the median shrugs off any residual cold-pop outlier.
        tax = stats.median(r - d for d, r in zip(direct_ms, routed_ms))
        # Per-stage p50 breakdown of where the tax goes, from the router's
        # own stage spans (docs/observability.md "Fleet observability"):
        # placement decision, breaker gate, retry attempt, proxied call.
        # The proxy stage CONTAINS the replica's work — only placement +
        # breaker (plus attempt minus proxy) are router-added time, so the
        # breakdown attributes the <2ms budget rather than re-measuring it.
        by_stage: dict[str, list[float]] = {}
        for trace in router.trace_store.traces():
            for stage, ms in trace.stage_ms().items():
                by_stage.setdefault(stage, []).append(ms)
        stage_p50 = {
            stage: round(stats.median(samples), 3)
            for stage, samples in sorted(by_stage.items())
        }
        return {
            "requests_per_arm": ROUNDS,
            "direct_p50_ms": round(direct_p50, 2),
            "router_p50_ms": round(router_p50, 2),
            "router_tax_ms": round(tax, 2),
            "router_stage_p50_ms": stage_p50,
            "warm_pop_rate": round(
                router.affinity_totals["warm"] / keyed if keyed else 0.0, 3
            ),
        }
    finally:
        if client is not None:
            await client.aclose()
        if router_runner is not None:
            await router_runner.cleanup()
        if router is not None:
            await router.stop()
        for executor, runner, _url in replicas:
            await runner.cleanup()
            await executor.aclose()


async def measure_session_latency_p50_ms(
    binary: Path, n: int = 12
) -> float | None:
    """Sessionful warm path (docs/sessions.md): p50 of execute №2..N inside
    ONE lease over the native pool — no workspace restore, snapshot
    deferred — the number to hold against ``latency_warm_p50_ms`` (each of
    whose executes pays a fresh checkout + full snapshot round-trip)."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage
    from bee_code_interpreter_tpu.sessions import SessionManager

    tmp = Path(tempfile.mkdtemp(prefix="bench-sess-"))
    config = Config(
        file_storage_path=str(tmp / "objects"),
        local_workspace_root=str(tmp / "ws"),
        executor_pod_queue_target_length=2,
        disable_dep_install=True,
    )
    storage = Storage(tmp / "objects")
    executor = NativeProcessCodeExecutor(
        storage=storage, config=config, binary=binary
    )
    manager = SessionManager(
        executor, storage, max_sessions=1, ttl_s=300, idle_s=300
    )
    try:
        await executor.fill_sandbox_queue()
        session = await manager.create()
        samples: list[float] = []
        for i in range(n):
            if i:
                # REPL pacing: the server re-warms its interpreter after
                # each claim; a real session's think-time overlaps that
                # preload, so back-to-back hammering would measure a
                # throughput ceiling, not the REPL turn latency (same
                # rationale as the stateless measurement's pacing).
                await asyncio.sleep(0.2)
            t0 = time.perf_counter()
            _, outcome = await manager.execute(
                session.session_id, LATENCY_PAYLOAD
            )
            if outcome.stdout != "42\n":
                raise RuntimeError(f"session payload failed: {outcome.stderr}")
            if i:  # execute №1 pays the cold in-lease warmup; 2..N is the REPL rate
                samples.append(time.perf_counter() - t0)
        await manager.release(session.session_id)
        return statistics.median(samples) * 1000
    finally:
        await manager.close_all()
        executor.shutdown()


TTFB_PAYLOAD = (
    "import time\nprint('first', flush=True)\ntime.sleep(0.5)\nprint('last')"
)


async def measure_streaming_ttfb_ms() -> float | None:
    """Time-to-first-stdout-byte through the streaming path (in-process
    executor: the chunked read loop itself, no pool noise): the payload
    flushes immediately then sleeps, so TTFB << total proves chunks flow
    while the run is still going."""
    from bee_code_interpreter_tpu.services.local_code_executor import (
        LocalCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="bench-ttfb-"))
    executor = LocalCodeExecutor(
        storage=Storage(tmp / "objects"),
        workspace_root=tmp / "ws",
        disable_dep_install=True,
        execution_timeout_s=30.0,
    )
    first_chunk_at: list[float] = []
    t0 = time.perf_counter()

    async def on_event(kind: str, _text: str) -> None:
        if kind == "stdout" and not first_chunk_at:
            first_chunk_at.append(time.perf_counter())

    result = await executor.execute_stream(TTFB_PAYLOAD, on_event=on_event)
    total = time.perf_counter() - t0
    if result.exit_code != 0 or not first_chunk_at:
        raise RuntimeError(f"ttfb payload failed: {result.stderr}")
    ttfb = (first_chunk_at[0] - t0) * 1000
    if ttfb >= total * 1000 * 0.9:
        # The first byte arrived with the end of the run: that is buffered
        # delivery wearing a streaming hat, not a TTFB.
        raise RuntimeError(f"no early chunk: ttfb {ttfb:.0f}ms of {total * 1000:.0f}ms total")
    return ttfb


def diagnose_tpu_failure(probes: list[dict], attempts: list[dict]) -> str:
    """Machine-readable reason the headline number is absent, naming the
    failing stage (probe vs init vs payload) — VERDICT r2 next-round #1."""
    probe = probes[-1] if probes else {}
    healthy = [p for p in probes if p.get("ok")]
    if not healthy:
        window = probes[-1].get("at_s", 0.0) if probes else 0.0
        return (
            f"tpu_backend_unreachable: {probe.get('error', 'probe failed')} "
            f"({len(probes)} probes over {window:.0f}s, none healthy)"
        )
    if all(p.get("platform") != "tpu" for p in healthy):
        return (
            f"no_tpu_device: jax backend here is '{probe.get('platform')}' "
            f"({probe.get('device_count')} devices)"
        )
    last = attempts[-1] if attempts else {}
    if last.get("payload_platform") == "cpu":
        return (
            "payload_ran_on_cpu: the probe saw a TPU backend but the "
            "executor sandbox ran the payload on CPU (accelerator env not "
            "passed through / probe-executor platform mismatch)"
        )
    text = (last.get("error", "") + " " + last.get("stderr_tail", "")).lower()
    if "timed out" in text or "exit -1" in text:
        return (
            "payload_timeout: chip probed ok but the matmul payload exceeded "
            "its budget (backend init or compile hung in-sandbox)"
        )
    return f"payload_error: {last.get('error', 'unknown')}"


def compact_probes(probes: list[dict]) -> list[dict]:
    """Probe history sized for a BENCH artifact: stderr tails only on the
    last entry, middle of a long wait elided (first 2 + last 6 kept)."""
    out = []
    for p in probes:
        p = dict(p)
        p.pop("stderr_tail", None)
        out.append(p)
    if probes and "stderr_tail" in probes[-1]:
        out[-1]["stderr_tail"] = probes[-1]["stderr_tail"]
    if len(out) > 8:
        elided = len(out) - 8
        out = out[:2] + [{"elided_probes": elided}] + out[-6:]
    return out


def hardware_evidence() -> list[dict]:
    """Latest TPU_EVIDENCE.jsonl entry per case — dated, git-attributed
    measurements captured whenever the tunnel was healthy, embedded so even
    a wedged driver run carries hardware evidence (VERDICT r3 #1b)."""
    try:
        from bee_code_interpreter_tpu.utils import evidence

        return evidence.latest_per_case()
    except Exception as e:  # the ledger must never take down the bench
        return [{"error": f"ledger unreadable: {e}"}]


def record_evidence(case: str, payload: dict) -> None:
    try:
        from bee_code_interpreter_tpu.utils import evidence

        evidence.record(case, payload, script="bench.py")
    except Exception as e:
        print(f"evidence ledger append failed: {e}", file=sys.stderr)


def _install_kill_safe_emit(state: dict) -> None:
    """If the driver kills a patient bench run mid-wait (SIGTERM/SIGINT),
    emit the complete CPU-fallback artifact — probes so far, diagnosis,
    ledger evidence — instead of dying with no output. The one JSON line is
    the whole contract; a timeout must shorten the patience, not void it."""

    def emit_and_die(signum: int, frame) -> None:
        if state.get("emitted"):
            os._exit(1)
        state["emitted"] = True
        tpu_gflops = state.get("tpu_gflops")
        if tpu_gflops is not None:  # headline landed; only auxiliaries lost
            result = {
                "metric": "dense matmul GFLOPS/chip via /v1/execute "
                          "(bf16 32768^3 jit chain)",
                "value": round(tpu_gflops, 1),
                "unit": "GFLOPS",
                "vs_baseline": round(tpu_gflops / RECORDED_CPU_GFLOPS, 2),
                "note": f"killed_by_signal_{signum} before aux measurements; "
                        "vs_baseline uses the recorded CPU figure",
            }
        else:
            result = {
                "metric": "dense matmul GFLOPS via /v1/execute "
                          "(CPU fallback - no TPU reachable)",
                "value": RECORDED_CPU_GFLOPS,
                "unit": "GFLOPS",
                "vs_baseline": 1.0,
                "tpu_diagnosis": (
                    f"killed_by_signal_{signum}_during_patience: "
                    + diagnose_tpu_failure(state["probes"], state["attempts"])
                ),
            }
        result.update(
            tpu_probes=compact_probes(state["probes"]),
            tpu_attempts=state["attempts"],
            latency_warm_p50_ms=None,
            cpu_baseline_gflops=RECORDED_CPU_GFLOPS,
            cpu_baseline_source="recorded",
            hardware_evidence=hardware_evidence(),
        )
        print(json.dumps(result), flush=True)
        os._exit(1)

    signal.signal(signal.SIGTERM, emit_and_die)
    signal.signal(signal.SIGINT, emit_and_die)


def _attempt_tpu_payload(state: dict, timeout_s: float) -> float | None:
    """One bounded run of the TPU payload through the service path. Returns
    GFLOPS only if the payload itself reports it ran ON a TPU — a
    CPU-mechanics run must never masquerade as the per-chip headline."""
    t0 = time.time()
    try:
        values = asyncio.run(
            run_payload_values(
                TPU_PAYLOAD, {}, timeout_s=timeout_s, marker="RESULT_GFLOPS"
            )
        )
        gflops, on_tpu = values[0], bool(values[1]) if len(values) > 1 else False
        entry = {
            "ok": on_tpu,
            "seconds": round(time.time() - t0, 1),
            "payload_platform": "tpu" if on_tpu else "cpu",
        }
        state["attempts"].append(entry)
        if on_tpu:
            print(f"tpu: {gflops:.1f} GFLOPS", file=sys.stderr)
            return gflops
        print(
            f"payload ran but on CPU ({gflops:.1f} GFLOPS) - not the "
            "headline", file=sys.stderr,
        )
        return None
    except Exception as e:
        entry = {
            "ok": False,
            "seconds": round(time.time() - t0, 1),
            "error": str(e)[:300],
        }
        stderr_tail = getattr(e, "stderr", "")
        if stderr_tail:
            entry["stderr_tail"] = stderr_tail[-400:]
        state["attempts"].append(entry)
        print(f"tpu payload attempt failed: {e}", file=sys.stderr)
        return None


def patient_tpu_capture(state: dict, patience_s: float) -> float | None:
    """PAYLOAD-FIRST measure loop (round-4 tunnel discovery: the tunnel may
    serve only ONE jax client per healthy window, and a killed client holds
    it wedged — so the first client must BE the measurement; a throwaway
    jax.devices() probe can burn the whole window). One bounded payload
    attempt runs immediately: on a healthy chip the headline lands with no
    probe at all. A payload that completes ON CPU means the sandbox env has
    no TPU — waiting cannot help, so one diagnostic probe is recorded and
    the capture returns. Only after a failed (hung/errored) attempt does
    the probe loop take over: re-probing on a gentle cadence up to
    ``patience_s``, re-attempting whenever a probe succeeds. Every
    probe/attempt is appended to ``state`` and ends up in the JSON."""
    t_start = time.time()
    deadline = t_start + patience_s
    # the first attempt respects a short patience budget (capture-on-healthy
    # runs bench with BCI_BENCH_TPU_PATIENCE_S=180) but never goes below the
    # time a healthy chip actually needs (init+compile can take ~90 s)
    gflops = _attempt_tpu_payload(state, min(210.0, max(patience_s, 90.0)))
    if gflops is not None:
        return gflops
    if state["attempts"] and (
        state["attempts"][-1].get("payload_platform") == "cpu"
    ):
        probe = probe_tpu()
        probe["at_s"] = round(time.time() - t_start, 1)
        state["probes"].append(probe)
        return None
    while time.time() < deadline:
        probe = probe_tpu()
        probe["at_s"] = round(time.time() - t_start, 1)
        state["probes"].append(probe)
        print(f"tpu probe: {probe}", file=sys.stderr)
        if probe.get("ok") and probe.get("platform") != "tpu":
            # real backend, no chip: waiting cannot help — but the payload
            # runs through the executor, whose env (accelerator
            # passthrough) is not guaranteed identical to the probe's
            return _attempt_tpu_payload(state, 90.0)
        if probe.get("ok"):
            for timeout_s in (210.0, 90.0):
                gflops = _attempt_tpu_payload(state, timeout_s)
                if gflops is not None:
                    return gflops
        now = time.time()
        if now >= deadline:
            return None
        wait = min(TPU_PROBE_INTERVAL_S, deadline - now)
        print(
            f"tpu wedged; re-probing in {wait:.0f}s "
            f"({deadline - now:.0f}s of patience left)",
            file=sys.stderr,
        )
        time.sleep(wait)
    return None


CAPACITY_ARTIFACT = REPO / "CAPACITY_r01.json"
# The at-SLO p99 threshold for the knee search: generous against the warm
# execute p50 (tens of ms) so the knee marks queueing collapse, not jitter.
CAPACITY_SLO_P99_MS = 1500.0
CAPACITY_PROBE_S = 4.0


def _capacity_free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _capacity_replica(binary: Path, tmp: Path, shared_root: Path, index: int) -> dict:
    """One COMPLETE capacity-instrumented replica over the native pool:
    real HTTP edge + admission + SLO engine + DemandTracker/Forecaster
    wired into GET /v1/autoscale — the production edge shape the loadgen
    measures, sharing a snapshot root with its siblings."""
    from aiohttp import web

    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.observability import (
        DemandTracker,
        Forecaster,
        SloEngine,
        parse_objectives,
    )
    from bee_code_interpreter_tpu.resilience import AdmissionController
    from bee_code_interpreter_tpu.resilience.autoscaler import autoscale_snapshot
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import (
        SharedDirectoryBackend,
        Storage,
    )
    from bee_code_interpreter_tpu.sessions import SessionManager
    from bee_code_interpreter_tpu.utils.metrics import Registry

    metrics = Registry()
    demand = DemandTracker(window_s=30.0, metrics=metrics)
    forecaster = Forecaster(
        demand, peak_window_s=10.0, max_horizon_s=5.0, metrics=metrics
    )
    storage = Storage(backend=SharedDirectoryBackend(shared_root))
    config = Config(
        file_storage_path=str(shared_root),
        local_workspace_root=str(tmp / f"ws-{index}"),
        executor_pod_queue_target_length=2,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=storage, config=config, binary=binary, metrics=metrics
    )
    executor.journal.add_sink(demand.on_fleet_event)
    await executor.fill_sandbox_queue()
    slo = SloEngine(parse_objectives(99.5, None), metrics=metrics)
    admission = AdmissionController(
        max_in_flight=8,
        max_queue=16,
        retry_after_s=0.2,
        metrics=metrics,
        demand=demand,
    )
    sessions = SessionManager(
        executor, storage, max_sessions=4, ttl_s=300, idle_s=300,
        metrics=metrics,
    )
    app = create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        admission=admission,
        slo=slo,
        sessions=sessions,
        fleet=executor.journal,
        autoscale=lambda: autoscale_snapshot(
            demand=demand, forecaster=forecaster, slo=slo
        ),
    )
    runner = web.AppRunner(app)
    await runner.setup()
    port = _capacity_free_port()
    await web.TCPSite(runner, "127.0.0.1", port).start()
    return {
        "name": f"r{index}",
        "url": f"http://127.0.0.1:{port}",
        "executor": executor,
        "runner": runner,
        "sessions": sessions,
    }


def _capacity_point(point: dict) -> dict:
    """One p99-vs-load curve point for the artifact: the verdict plus the
    quantiles that explain it, without the full per-sample dump."""
    result = point.get("result") or {}
    latency = result.get("latency_ms") or {}
    rec = point.get("recommendation") or {}

    def r1(value):
        return None if value is None else round(value, 1)

    warm = point.get("warm_pop_ratio")
    return {
        "offered_rps": round(point["offered_rps"], 2),
        "achieved_rps": r1(result.get("achieved_rps")),
        "sustained": point["sustained"],
        "reasons": point["reasons"],
        "p50_ms": r1(latency.get("p50")),
        "p95_ms": r1(latency.get("p95")),
        "p99_ms": r1(latency.get("p99")),
        "sheds": result.get("sheds"),
        "errors": result.get("errors"),
        "warm_pop_ratio": None if warm is None else round(warm, 3),
        "recommended_replicas": rec.get("target_replicas"),
    }


async def _capacity_probe_config(
    client, base_url: str, *, replicas: int, router=None, hi_rps: float
) -> dict:
    """Knee-search one configuration, then hold a 10x flash crowd against
    it and record what the observability plane said while it burned."""
    from bee_code_interpreter_tpu.loadgen import (
        CapacityReporter,
        FlashCrowd,
        OpenLoopGenerator,
        TrafficMix,
        find_knee,
    )

    session_ids: list[str] = []
    response = await client.post(f"{base_url}/v1/sessions", json={})
    if response.status_code == 200:
        session_ids.append(response.json()["session_id"])
    kinds = (
        (("execute", 8.0), ("session", 1.0), ("stream", 1.0))
        if session_ids
        else (("execute", 9.0), ("stream", 1.0))
    )
    generator = OpenLoopGenerator(
        client, base_url, mix=TrafficMix(kinds=kinds), session_ids=session_ids
    )
    reporter = CapacityReporter(client, base_url, router=router)
    knee, probes = await find_knee(
        generator,
        lo_rps=1.0,
        hi_rps=hi_rps,
        duration_s=CAPACITY_PROBE_S,
        p99_ms=CAPACITY_SLO_P99_MS,
        reporter=reporter,
        iterations=5,
        settle_s=1.0,
        drain_timeout_s=20.0,
        on_probe=lambda p: print(
            f"capacity probe {p['offered_rps']:.2f} rps: "
            f"{'sustained' if p['sustained'] else p['reasons']}",
            file=sys.stderr,
        ),
    )
    base = max(1.0, knee / 2.0)
    crowd = await generator.run(
        FlashCrowd(
            base_rps=base,
            duration_s=8.0,
            crowd_start_s=2.0,
            crowd_s=2.0,
            multiplier=10.0,
        ),
        label="flash-crowd",
        drain_timeout_s=30.0,
    )
    scrape = await reporter.scrape()
    config = {
        "replicas": replicas,
        "router": router is not None,
        "max_sustained_rps": round(knee, 2),
        "curve": [_capacity_point(p) for p in probes],
        "flash_crowd": {
            **crowd.to_dict(),
            "shed_ledger": crowd.shed_ledger(),
            "warm_pop_ratio": scrape.get("warm_pop_ratio"),
            "recommendation": scrape.get("recommendation"),
            "fast_burn": scrape.get("fast_burn"),
        },
    }
    stage_p50 = reporter.stage_p50_ms()
    if stage_p50:
        config["router_stage_p50_ms"] = stage_p50
    return config


async def measure_capacity(binary: Path) -> dict:
    """The `capacity` phase (docs/capacity.md): max-sustained-rps-at-SLO
    for (a) one replica hit directly and (b) three replicas behind the
    real FleetRouter — measured by the open-loop generator, judged by the
    federated SLO/autoscale plane, published as CAPACITY_r01.json."""
    import httpx
    from aiohttp import web

    from bee_code_interpreter_tpu.fleet import FleetRouter, create_router_app

    configs: dict[str, dict] = {}
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # --- config A: one replica, clients hit its edge directly
        tmp = Path(tempfile.mkdtemp(prefix="bench-capacity-solo-"))
        replica = await _capacity_replica(binary, tmp, tmp / "objects", 0)
        try:
            configs["replica-1"] = await _capacity_probe_config(
                client, replica["url"], replicas=1, hi_rps=10.0
            )
        finally:
            await replica["sessions"].close_all()
            await replica["runner"].cleanup()
            await replica["executor"].aclose()

        # --- config B: three replicas behind the fleet router (live
        # background refresh: the production edge shape, router tax and
        # retry policy included in every sample)
        tmp = Path(tempfile.mkdtemp(prefix="bench-capacity-fleet-"))
        replicas = [
            await _capacity_replica(binary, tmp, tmp / "objects", i)
            for i in range(3)
        ]
        router = FleetRouter(
            [(r["name"], r["url"]) for r in replicas],
            refresh_interval_s=1.0,
            dead_after_s=5.0,
        )
        router_runner = web.AppRunner(create_router_app(router))
        await router_runner.setup()
        router_port = _capacity_free_port()
        await web.TCPSite(router_runner, "127.0.0.1", router_port).start()
        await router.refresh_once()
        router.start()
        try:
            configs["router-3"] = await _capacity_probe_config(
                client,
                f"http://127.0.0.1:{router_port}",
                replicas=3,
                router=router,
                hi_rps=16.0,
            )
        finally:
            await router.stop()
            await router_runner.cleanup()
            for r in replicas:
                await r["sessions"].close_all()
                await r["runner"].cleanup()
                await r["executor"].aclose()
    finally:
        await client.aclose()
    return configs


def capacity_main() -> None:
    """`python bench.py capacity`: measure the SLO-vs-load curves and
    write the CAPACITY_r01.json artifact (plus one summary line on
    stdout, same one-line contract as the main bench)."""
    binary = ensure_native_binary()
    if binary is None:
        print(
            json.dumps({"error": "no native executor binary; capacity "
                        "phase needs `make -C executor`"}),
            flush=True,
        )
        sys.exit(1)
    t0 = time.time()
    configs = asyncio.run(
        asyncio.wait_for(measure_capacity(binary), timeout=540.0)
    )
    artifact = {
        "version": "r01",
        "generated_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "host": {"platform": sys.platform, "cpus": os.cpu_count()},
        "slo": {
            "availability_pct": 99.5,
            "p99_ms": CAPACITY_SLO_P99_MS,
            "error_budget": 0.005,
            "shed_budget": 0.01,
        },
        "probe": {
            "duration_s": CAPACITY_PROBE_S,
            "mix": "execute 8 : session 1 : stream 1, heavy-tail cost classes",
            "method": "bisection on the sustained predicate (docs/capacity.md)",
        },
        "configs": configs,
        "wall_s": round(time.time() - t0, 1),
    }
    CAPACITY_ARTIFACT.write_text(json.dumps(artifact, indent=2) + "\n")
    print(
        json.dumps(
            {
                "metric": "max sustained rps at SLO (p99<=1500ms, err<=0.5%, shed<=1%)",
                "configs": {
                    name: c["max_sustained_rps"]
                    for name, c in configs.items()
                },
                "artifact": CAPACITY_ARTIFACT.name,
            }
        ),
        flush=True,
    )


def main() -> None:
    # --- 1. the headline TPU number (runs first; ambient accelerator env —
    # including any tunnel plugin vars — flows through the executor's
    # passthrough so the payload sees the real chip). Patient: see
    # patient_tpu_capture. A healthy chip needs ~90 s total (init ~20-40,
    # compile ~20-40, 4 timed chains ~25); a wedged tunnel costs up to
    # TPU_PATIENCE_S before the CPU fallback, with a kill-safe artifact if
    # the driver's clock is shorter than ours.
    state: dict = {"probes": [], "attempts": [], "emitted": False}
    _install_kill_safe_emit(state)
    tpu_gflops = patient_tpu_capture(state, TPU_PATIENCE_S)
    state["tpu_gflops"] = tpu_gflops
    tpu_probes: list[dict] = state["probes"]
    tpu_attempts: list[dict] = state["attempts"]
    chip_likely = any(
        p.get("ok") and p.get("platform") == "tpu" for p in tpu_probes
    )
    if tpu_gflops is not None:
        record_evidence(
            "dense_matmul",
            {"gflops": round(tpu_gflops, 1),
             "payload": "bf16 32768^3 jit chain via /v1/execute"},
        )

    # --- 1b. flash-attention kernel evidence (guarded; extra field only;
    # runs only when the headline already landed, so it can never cost the
    # main metric its window) ----------------------------------------------
    flash: dict | None = None
    if tpu_gflops is not None and chip_likely:
        try:
            fl, xl = asyncio.run(
                run_payload_values(
                    FLASH_PAYLOAD, {}, timeout_s=240.0, marker="RESULT_FLASH"
                )
            )
            # The comparator is reference_attention compiled by XLA (a naive
            # einsum+softmax), NOT a tuned fused-attention lowering — the
            # field name says exactly that (ADVICE r3 #3).
            flash = {
                "tflops": fl,
                "xla_ref_tflops": xl,
                "speedup_vs_xla_ref": round(fl / xl, 2),
                "shape": "B4 H16 L4096 D128 bf16 causal",
            }
            print(f"flash attention: {flash}", file=sys.stderr)
            record_evidence("flash_attention", flash)
        except Exception as e:
            print(f"flash case failed (field omitted): {e}", file=sys.stderr)

    # --- 2. CPU baseline (guarded: can only degrade vs_baseline) ----------
    scrub_tunnel_vars()
    cpu_gflops: float | None = None
    cpu_source = "measured"
    try:
        cpu_gflops = asyncio.run(
            run_payload(
                CPU_PAYLOAD,
                {"JAX_PLATFORMS": "cpu", "BCI_XLA_REROUTE": "0"},
                timeout_s=90.0,
            )
        )
        print(f"cpu baseline: {cpu_gflops:.1f} GFLOPS", file=sys.stderr)
    except Exception as e:
        print(
            f"cpu baseline failed ({e}); using recorded "
            f"{RECORDED_CPU_GFLOPS} GFLOPS",
            file=sys.stderr,
        )
        cpu_gflops = RECORDED_CPU_GFLOPS
        cpu_source = "recorded"

    # --- 3. warm-pool execute latency (guarded; extra field) --------------
    latency_p50_ms: float | None = None
    latency_phases: dict | None = None
    binary = ensure_native_binary()
    if binary is not None:
        try:
            measured = asyncio.run(
                asyncio.wait_for(measure_warm_latency_p50_ms(binary), timeout=90.0)
            )
            if measured is not None:
                latency_p50_ms, latency_phases = measured
                print(
                    f"warm execute p50: {latency_p50_ms:.1f} ms "
                    f"(phases {latency_phases})",
                    file=sys.stderr,
                )
        except Exception as e:
            print(f"latency measurement failed: {e}", file=sys.stderr)

    # --- 3a. sessionful warm path + streaming TTFB (guarded; extra fields;
    # docs/sessions.md — the lease amortizes the snapshot tax the stateless
    # number above pays per execute) -----------------------------------------
    session_p50_ms: float | None = None
    if binary is not None:
        try:
            session_p50_ms = asyncio.run(
                asyncio.wait_for(
                    measure_session_latency_p50_ms(binary), timeout=90.0
                )
            )
            print(
                f"sessionful warm execute p50: {session_p50_ms:.1f} ms "
                f"(stateless warm p50: {latency_p50_ms} ms)",
                file=sys.stderr,
            )
        except Exception as e:
            print(f"session latency measurement failed: {e}", file=sys.stderr)
    streaming_ttfb_ms: float | None = None
    try:
        streaming_ttfb_ms = asyncio.run(
            asyncio.wait_for(measure_streaming_ttfb_ms(), timeout=60.0)
        )
        print(f"streaming TTFB: {streaming_ttfb_ms:.1f} ms", file=sys.stderr)
    except Exception as e:
        print(f"streaming TTFB measurement failed: {e}", file=sys.stderr)

    # --- 3a'. surge phase (guarded; extra field only; docs/autoscaling.md):
    # a load step absorbed by the predictive autoscaler (act) vs the static
    # pool (off) — time-to-absorb + sheds, the capacity loop's own numbers
    surge: dict | None = None
    if binary is not None:
        try:
            surge = asyncio.run(
                asyncio.wait_for(measure_surge(binary), timeout=150.0)
            )
            print(f"surge A/B: {surge}", file=sys.stderr)
        except Exception as e:
            print(f"surge measurement failed (field omitted): {e}", file=sys.stderr)

    # --- 3a''. router phase (guarded; extra field only; docs/fleet.md):
    # p50 through the fleet router vs direct-to-replica on the native pool
    # (the routing tax, budget < 2ms added p50) + warm-affinity hit rate
    router_phase: dict | None = None
    if binary is not None:
        try:
            router_phase = asyncio.run(
                asyncio.wait_for(measure_router(binary), timeout=150.0)
            )
            print(f"router phase: {router_phase}", file=sys.stderr)
        except Exception as e:
            print(f"router measurement failed (field omitted): {e}", file=sys.stderr)

    # --- 3a'''. fairness phase (guarded; extra field only; docs/tenancy.md):
    # victim-tenant p50 with vs without a 100x-quota abusive flood — the
    # multi-tenant isolation budget (< 10% degradation), measured
    fairness: dict | None = None
    if binary is not None:
        try:
            fairness = asyncio.run(
                asyncio.wait_for(measure_fairness(binary), timeout=150.0)
            )
            print(f"fairness phase: {fairness}", file=sys.stderr)
        except Exception as e:
            print(
                f"fairness measurement failed (field omitted): {e}",
                file=sys.stderr,
            )

    # --- 3b. serving phase (guarded; extra field only): tokens/sec + TTFT
    # p50/p95 + inter-token latency with a measured instrumentation on/off
    # A/B (models/serving_bench.py; docs/observability.md "Serving
    # observability") -------------------------------------------------------
    serving: dict | None = None
    try:
        # PYTHONPATH carries the repo into the sandbox: the payload imports
        # the serving stack itself, and the executor drops the host's
        # import path (request-supplied entries survive the scrub)
        serving = asyncio.run(run_payload_json(
            SERVING_PAYLOAD,
            {"JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)},
            timeout_s=420.0,
            marker="RESULT_SERVING_JSON",
        ))
        print(f"serving bench: {serving}", file=sys.stderr)
    except Exception as e:
        print(f"serving bench failed (field omitted): {e}", file=sys.stderr)

    if tpu_gflops is not None:
        result = {
            "metric": "dense matmul GFLOPS/chip via /v1/execute (bf16 32768^3 jit chain)",
            "value": round(tpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": round(tpu_gflops / cpu_gflops, 2),
        }
    else:  # no chip reachable: report the CPU path honestly, with the reason
        result = {
            "metric": "dense matmul GFLOPS via /v1/execute (CPU fallback - no TPU reachable)",
            "value": round(cpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": 1.0,
            "tpu_diagnosis": diagnose_tpu_failure(tpu_probes, tpu_attempts),
        }
    result["tpu_probes"] = compact_probes(tpu_probes)
    result["tpu_attempts"] = tpu_attempts
    if flash is not None:
        result["flash_attention"] = flash
    result["latency_warm_p50_ms"] = (
        round(latency_p50_ms, 1) if latency_p50_ms is not None else None
    )
    if latency_phases is not None:
        result["latency_phases_p50"] = latency_phases
    # Sessionful warm path (execute №2..N inside one lease, restore skipped
    # and snapshot deferred) next to the stateless number it undercuts, and
    # time-to-first-stdout-byte through the streaming path.
    result["latency_session_p50_ms"] = (
        round(session_p50_ms, 1) if session_p50_ms is not None else None
    )
    result["streaming_ttfb_ms"] = (
        round(streaming_ttfb_ms, 1) if streaming_ttfb_ms is not None else None
    )
    if surge is not None:
        result["surge"] = surge
    if router_phase is not None:
        result["router"] = router_phase
    if fairness is not None:
        result["fairness"] = fairness
    if serving is not None:
        result["serving"] = serving
    result["cpu_baseline_gflops"] = round(cpu_gflops, 1)
    # "recorded" = the live CPU run failed and vs_baseline uses the recorded
    # machine-class figure — a constant must never masquerade as a measurement
    result["cpu_baseline_source"] = cpu_source
    # Dated, git-attributed measurements from healthy-tunnel windows — the
    # capture-on-healthy ledger rides along in every artifact.
    result["hardware_evidence"] = hardware_evidence()
    # Committed to emitting: neutralize the kill-safe handler BEFORE the
    # print (a SIGTERM interleaving a second JSON line into a half-written
    # one would corrupt the artifact; ignoring it for the final write keeps
    # the one-line contract either way).
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    state["emitted"] = True
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "capacity":
        capacity_main()
    else:
        main()
