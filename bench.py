#!/usr/bin/env python
"""Headline benchmark: dense-matmul GFLOPS/chip driven through /v1/execute.

Measures the BASELINE.json north-star metric — the benchmark-numpy dense
matmul payload submitted through the service's real execution path (the
sandbox executor with the TPU runtime shim), reported as GFLOPS on the
attached chip. ``vs_baseline`` compares against the same payload on the host
CPU path (the reference's only execution substrate; BASELINE.md "the
reference's CPU path is the comparison baseline").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "GFLOPS", "vs_baseline": N,
     "latency_warm_p50_ms": N | null, "cpu_baseline_gflops": N}

Extra detail lines go to stderr.

Ordering and guards (round-1 lesson, BENCH_r01.json rc=1): the TPU
measurement — the number this benchmark exists to produce — runs FIRST and
nothing that happens to the auxiliary measurements can take it down. The CPU
baseline runs second, try/except-guarded, in a process env scrubbed of
accelerator-tunnel plugin vars (PALLAS_*/AXON_* hook jax backend init even
under JAX_PLATFORMS=cpu and block on a single-client tunnel) with the reroute
opted out via the *request env* (not in-script — numpy may already be proxied
by the time user code runs). If the live baseline fails anyway, a recorded
baseline keeps ``vs_baseline`` meaningful and is flagged on stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
SHIM_DIR = REPO / "bee_code_interpreter_tpu" / "runtime" / "shim"

N = 32768
ITERS = 16

# The measured payload: a bf16 matmul chain under jit, the shape of work the
# MXU exists for. Chained with a data dependency (no loop hoisting), one
# device->host readback at the end. Written the way a sandbox user writes JAX.
# n=32768 keeps each matmul MXU-bound long enough to amortize loop/dispatch
# overhead (measured 186 TFLOPS = 94% of v5e bf16 peak vs 147 at n=8192); the
# one-time 1/128 pre-scale keeps the chain's magnitudes roughly stable without
# paying a per-iteration epilogue.
TPU_PAYLOAD = f"""
import time
import jax, jax.numpy as jnp
from jax import lax

n, iters = {N}, {ITERS}
if jax.devices()[0].platform == "cpu":
    n, iters = 1024, 4  # no accelerator: validate mechanics only
a = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype=jnp.bfloat16)

@jax.jit
def chain(a):
    a = a * jnp.bfloat16(1 / 128)
    def body(i, x):
        return a @ x
    return lax.fori_loop(0, iters, body, a).sum()

float(chain(a))  # compile + warm
best = float("inf")
for _ in range(3):
    t0 = time.time()
    float(chain(a))
    best = min(best, time.time() - t0)
print(f"RESULT_GFLOPS {{2 * n**3 * iters / best / 1e9:.1f}}")
"""

# Host-CPU baseline: the same kernel as the TPU chain — one-time 1/128
# pre-scale, then a pure data-dependent matmul chain with a single readback —
# through plain numpy (f32; numpy has no bf16), sized down (self-timed wall
# clock, as the reference's own benchmark payload does). n=2048 is enough to
# saturate the host BLAS; anything larger just risks the driver's clock.
CPU_PAYLOAD = """
import time
import numpy as np

n, iters = 2048, 8
a = np.random.rand(n, n).astype(np.float32) * np.float32(1 / 128)
x = a
t0 = time.time()
for _ in range(iters):
    x = a @ x
s = float(x.sum())
dt = time.time() - t0
print(f"RESULT_GFLOPS {2 * n**3 * iters / dt / 1e9:.1f}")
"""

# Live-CPU-baseline fallback: the same payload measured out-of-band on this
# machine class (round-1 verification run: 120 GFLOPS through the identical
# LocalCodeExecutor path). Used only if the live baseline fails; stderr says so.
RECORDED_CPU_GFLOPS = 120.0

LATENCY_PAYLOAD = "print(21 * 2)"

# Guarded extra evidence: the Pallas flash-attention kernel vs XLA's own
# fused attention, through the same execution path — so the kernel claims in
# BASELINE.md stop being builder-session-only. Timing by the
# (t_N - t_1)/(N-1) chain difference (utils/benchclock.py), which cancels
# the device->host readback RTT exactly. Shape and chain length are sized so
# the chain DOMINATES the ~70 ms tunnel RTT (flash ≈ 2.8 ms/call at the
# measured 99 TFLOPS → 31 extra calls ≈ 87 ms >> 1.2x guard margin) — a
# smaller shape would trip the sanity guard on every tunneled run and the
# field could never land. Cost on a healthy chip: 4 jit compiles (~25 s
# each worst-case) + ~4 s of timed chains, inside the 240 s budget.
FLASH_PAYLOAD = """
import time
import jax, jax.numpy as jnp
from jax import lax
from bee_code_interpreter_tpu.ops.flash_attention import flash_attention
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention
from bee_code_interpreter_tpu.utils.benchclock import chain_diff

B, H, L, D = 4, 16, 4096, 128
N = 32
q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, H, L, D), jnp.bfloat16)
           for i in range(3))

def chain(attn, length):
    @jax.jit
    def f(q, k, v):
        def body(c, _):
            return attn(c, k, v), None
        c, _ = lax.scan(body, q, None, length=length)
        return c.astype(jnp.float32).sum()
    return f

def per_call(attn, what):
    def best_of(f):
        float(f(q, k, v))
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(f(q, k, v))
            best = min(best, time.perf_counter() - t0)
        return best
    return chain_diff(best_of(chain(attn, N)), best_of(chain(attn, 1)), N, what)

t_fl = per_call(lambda q, k, v: flash_attention(q, k, v, True), "flash")
t_xl = per_call(
    lambda q, k, v: reference_attention(q, k, v, causal=True).astype(q.dtype),
    "xla",
)
flops = 2 * B * H * L * L * D  # causal: half of 4*B*H*L*L*D
print(f"RESULT_FLASH {flops / t_fl / 1e12:.2f} {flops / t_xl / 1e12:.2f}")
"""


def probe_tpu(timeout_s: float = 75.0) -> dict:
    """Bounded out-of-process probe of the JAX accelerator backend.

    Two rounds of driver artifacts couldn't distinguish "chip absent" from
    "backend init hung" from "payload too slow" (VERDICT r2 weak #1); this
    records which. A hung tunnel hangs the subprocess, not the bench.
    """
    t0 = time.time()
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; ds = jax.devices(); "
                "print('PROBE', ds[0].platform, len(ds))",
            ],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        seconds = round(time.time() - t0, 1)
        for line in out.stdout.splitlines():
            if line.startswith("PROBE "):
                _, platform, count = line.split()
                return {
                    "ok": True,
                    "seconds": seconds,
                    "platform": platform,
                    "device_count": int(count),
                }
        return {
            "ok": False,
            "seconds": seconds,
            "error": f"probe exited {out.returncode} without a device line",
            "stderr_tail": out.stderr[-400:],
        }
    except subprocess.TimeoutExpired as e:
        return {
            "ok": False,
            "seconds": round(time.time() - t0, 1),
            "error": f"jax.devices() hung past {timeout_s:.0f}s (wedged TPU tunnel)",
            "stderr_tail": ((e.stderr or b"").decode("utf-8", "replace"))[-400:],
        }


class PayloadError(RuntimeError):
    """Payload failure carrying the sandbox stderr for the bench artifact."""

    def __init__(self, msg: str, stderr: str = "") -> None:
        super().__init__(msg)
        self.stderr = stderr


async def run_payload(
    source: str, env: dict[str, str], timeout_s: float,
    marker: str = "RESULT_GFLOPS",
) -> float:
    values = await run_payload_values(source, env, timeout_s, marker)
    return values[0]


async def run_payload_values(
    source: str, env: dict[str, str], timeout_s: float, marker: str
) -> list[float]:
    """Execute through the service path; return the floats following
    ``marker`` on the payload's result line."""
    from bee_code_interpreter_tpu.services.local_code_executor import (
        LocalCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = tempfile.mkdtemp(prefix="bench-")
    executor = LocalCodeExecutor(
        storage=Storage(Path(tmp) / "objects"),
        workspace_root=Path(tmp) / "ws",
        disable_dep_install=True,
        execution_timeout_s=timeout_s,
        shim_dir=SHIM_DIR,
    )
    result = await executor.execute(source, env=env)
    if result.exit_code != 0:
        print(result.stderr, file=sys.stderr)
        raise PayloadError(
            f"payload failed (exit {result.exit_code})", stderr=result.stderr
        )
    for line in result.stdout.splitlines():
        if line.startswith(marker):
            return [float(tok) for tok in line.split()[1:]]
    raise PayloadError(f"no result in stdout: {result.stdout!r}")


def scrub_tunnel_vars() -> None:
    """Drop accelerator-tunnel plugin vars from THIS process (inherited by the
    executor's TPU_PASSTHROUGH_PREFIXES) so CPU-pinned payloads cannot be
    hijacked into a blocking TPU backend init. Called only after the TPU
    measurement — which needs those very vars — has completed."""
    from bee_code_interpreter_tpu.utils.envscrub import scrub_tunnel_plugin_vars

    scrub_tunnel_plugin_vars()


def ensure_native_binary() -> Path | None:
    """Build the C++ executor if needed — synchronously, OUTSIDE any event
    loop (a blocking subprocess inside a coroutine would stall the loop and
    defeat the asyncio.wait_for guard around the latency measurement)."""
    binary = REPO / "executor" / "build" / "executor-server"
    if binary.exists():
        return binary
    try:
        build = subprocess.run(
            ["make", "-C", str(REPO / "executor"), "-s"],
            capture_output=True,
            timeout=180,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"latency: executor build failed ({e})", file=sys.stderr)
        return None
    if build.returncode != 0 or not binary.exists():
        print("latency: no native executor binary", file=sys.stderr)
        return None
    return binary


async def measure_warm_latency_p50_ms(
    binary: Path, n: int = 20
) -> tuple[float, dict] | None:
    """p50 of a trivial execute through the warm native-executor pool, plus a
    per-phase p50 breakdown (acquire / upload / POST / in-sandbox / overhead /
    download) so a regressed number names its phase instead of inviting
    guesses about host load (VERDICT r2 weak #2). scripts/measure-latency.py
    is the full percentile harness."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="bench-lat-"))
    config = Config(
        file_storage_path=str(tmp / "objects"),
        local_workspace_root=str(tmp / "ws"),
        executor_pod_queue_target_length=4,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=Storage(tmp / "objects"), config=config, binary=binary
    )
    try:
        await executor.fill_sandbox_queue()
        samples: list[float] = []
        phase_samples: list[dict] = []
        for i in range(n):
            if i:
                # Pace requests: this measures warm-pool REQUEST latency, not
                # saturated throughput (back-to-back requests outrun the
                # refill pipeline and every pop hits a sandbox whose warm
                # interpreter is still preloading — that's a throughput
                # ceiling, a different metric). The sleep is excluded from
                # the samples.
                await asyncio.sleep(0.35)
            t0 = time.perf_counter()
            result = await executor.execute(LATENCY_PAYLOAD)
            if result.stdout != "42\n":
                raise RuntimeError(f"latency payload failed: {result.stderr}")
            samples.append(time.perf_counter() - t0)
            phase_samples.append(dict(executor.last_execute_phases))
        phases_p50 = {
            key: round(
                statistics.median(
                    float(p.get(key, 0.0)) for p in phase_samples
                ),
                1,
            )
            for key in (
                "acquire_ms",
                "upload_ms",
                "post_execute_ms",
                "sandbox_ms",
                "overhead_ms",
                "download_ms",
            )
        }
        phases_p50["warm_pop_rate"] = round(
            sum(1 for p in phase_samples if p.get("warm_pop")) / len(phase_samples),
            2,
        )
        return statistics.median(samples) * 1000, phases_p50
    finally:
        executor.shutdown()


def diagnose_tpu_failure(probe: dict, attempts: list[dict]) -> str:
    """Machine-readable reason the headline number is absent, naming the
    failing stage (probe vs init vs payload) — VERDICT r2 next-round #1."""
    if not probe.get("ok"):
        return f"tpu_backend_unreachable: {probe.get('error', 'probe failed')}"
    if probe.get("platform") != "tpu":
        return (
            f"no_tpu_device: jax backend here is '{probe.get('platform')}' "
            f"({probe.get('device_count')} devices)"
        )
    last = attempts[-1] if attempts else {}
    text = (last.get("error", "") + " " + last.get("stderr_tail", "")).lower()
    if "timed out" in text or "exit -1" in text:
        return (
            "payload_timeout: chip probed ok but the matmul payload exceeded "
            "its budget (backend init or compile hung in-sandbox)"
        )
    return f"payload_error: {last.get('error', 'unknown')}"


def main() -> None:
    # --- 1. the headline TPU number (runs first; ambient accelerator env —
    # including any tunnel plugin vars — flows through the executor's
    # passthrough so the payload sees the real chip) -----------------------
    # Self-diagnosing: a bounded out-of-process probe records whether the
    # backend is reachable at all, then the payload gets up to 3 attempts
    # spread across the window (a wedged tunnel can recover); every failure
    # lands in the JSON with its stderr tail. Budgets sized so the worst case
    # (probe 75 s + attempts 210+90+60 s) still leaves room for the CPU +
    # latency measurements inside the driver window. A healthy chip needs
    # ~90 s (init ~20-40, compile ~20-40, 4 timed chains ~25).
    tpu_probe = probe_tpu()
    print(f"tpu probe: {tpu_probe}", file=sys.stderr)
    chip_likely = tpu_probe.get("ok") and tpu_probe.get("platform") == "tpu"
    # An unreachable/CPU probe still gets one bounded attempt — tunnels recover
    attempt_budgets = [210.0, 90.0, 60.0] if chip_likely else [90.0]

    tpu_gflops: float | None = None
    tpu_attempts: list[dict] = []
    for timeout_s in attempt_budgets:
        t0 = time.time()
        try:
            tpu_gflops = asyncio.run(
                run_payload(TPU_PAYLOAD, {}, timeout_s=timeout_s)
            )
            tpu_attempts.append(
                {"ok": True, "seconds": round(time.time() - t0, 1)}
            )
            print(f"tpu: {tpu_gflops:.1f} GFLOPS", file=sys.stderr)
            break
        except Exception as e:
            entry: dict = {
                "ok": False,
                "seconds": round(time.time() - t0, 1),
                "error": str(e)[:300],
            }
            stderr_tail = getattr(e, "stderr", "")
            if stderr_tail:
                entry["stderr_tail"] = stderr_tail[-400:]
            tpu_attempts.append(entry)
            print(f"tpu payload attempt failed: {e}", file=sys.stderr)

    # --- 1b. flash-attention kernel evidence (guarded; extra field only;
    # runs only when the headline already landed, so it can never cost the
    # main metric its window) ----------------------------------------------
    flash: dict | None = None
    if tpu_gflops is not None and chip_likely:
        try:
            fl, xl = asyncio.run(
                run_payload_values(
                    FLASH_PAYLOAD, {}, timeout_s=240.0, marker="RESULT_FLASH"
                )
            )
            flash = {
                "tflops": fl,
                "xla_ref_tflops": xl,
                "speedup_vs_xla": round(fl / xl, 2),
                "shape": "B4 H16 L4096 D128 bf16 causal",
            }
            print(f"flash attention: {flash}", file=sys.stderr)
        except Exception as e:
            print(f"flash case failed (field omitted): {e}", file=sys.stderr)

    # --- 2. CPU baseline (guarded: can only degrade vs_baseline) ----------
    scrub_tunnel_vars()
    cpu_gflops: float | None = None
    cpu_source = "measured"
    try:
        cpu_gflops = asyncio.run(
            run_payload(
                CPU_PAYLOAD,
                {"JAX_PLATFORMS": "cpu", "BCI_XLA_REROUTE": "0"},
                timeout_s=90.0,
            )
        )
        print(f"cpu baseline: {cpu_gflops:.1f} GFLOPS", file=sys.stderr)
    except Exception as e:
        print(
            f"cpu baseline failed ({e}); using recorded "
            f"{RECORDED_CPU_GFLOPS} GFLOPS",
            file=sys.stderr,
        )
        cpu_gflops = RECORDED_CPU_GFLOPS
        cpu_source = "recorded"

    # --- 3. warm-pool execute latency (guarded; extra field) --------------
    latency_p50_ms: float | None = None
    latency_phases: dict | None = None
    binary = ensure_native_binary()
    if binary is not None:
        try:
            measured = asyncio.run(
                asyncio.wait_for(measure_warm_latency_p50_ms(binary), timeout=90.0)
            )
            if measured is not None:
                latency_p50_ms, latency_phases = measured
                print(
                    f"warm execute p50: {latency_p50_ms:.1f} ms "
                    f"(phases {latency_phases})",
                    file=sys.stderr,
                )
        except Exception as e:
            print(f"latency measurement failed: {e}", file=sys.stderr)

    if tpu_gflops is not None:
        result = {
            "metric": "dense matmul GFLOPS/chip via /v1/execute (bf16 32768^3 jit chain)",
            "value": round(tpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": round(tpu_gflops / cpu_gflops, 2),
        }
    else:  # no chip reachable: report the CPU path honestly, with the reason
        result = {
            "metric": "dense matmul GFLOPS via /v1/execute (CPU fallback - no TPU reachable)",
            "value": round(cpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": 1.0,
            "tpu_diagnosis": diagnose_tpu_failure(tpu_probe, tpu_attempts),
        }
    result["tpu_probe"] = tpu_probe
    result["tpu_attempts"] = tpu_attempts
    if flash is not None:
        result["flash_attention"] = flash
    result["latency_warm_p50_ms"] = (
        round(latency_p50_ms, 1) if latency_p50_ms is not None else None
    )
    if latency_phases is not None:
        result["latency_phases_p50"] = latency_phases
    result["cpu_baseline_gflops"] = round(cpu_gflops, 1)
    # "recorded" = the live CPU run failed and vs_baseline uses the recorded
    # machine-class figure — a constant must never masquerade as a measurement
    result["cpu_baseline_source"] = cpu_source
    print(json.dumps(result))


if __name__ == "__main__":
    main()
