#!/usr/bin/env python
"""Headline benchmark: dense-matmul GFLOPS/chip driven through /v1/execute.

Measures the BASELINE.json north-star metric — the benchmark-numpy dense
matmul payload submitted through the service's real execution path (the
sandbox executor with the TPU runtime shim), reported as GFLOPS on the
attached chip. ``vs_baseline`` compares against the same payload on the host
CPU path (the reference's only execution substrate; BASELINE.md "the
reference's CPU path is the comparison baseline").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "GFLOPS", "vs_baseline": N,
     "latency_warm_p50_ms": N | null, "cpu_baseline_gflops": N}

Extra detail lines go to stderr.

Ordering and guards (round-1 lesson, BENCH_r01.json rc=1): the TPU
measurement — the number this benchmark exists to produce — runs FIRST and
nothing that happens to the auxiliary measurements can take it down. The CPU
baseline runs second, try/except-guarded, in a process env scrubbed of
accelerator-tunnel plugin vars (PALLAS_*/AXON_* hook jax backend init even
under JAX_PLATFORMS=cpu and block on a single-client tunnel) with the reroute
opted out via the *request env* (not in-script — numpy may already be proxied
by the time user code runs). If the live baseline fails anyway, a recorded
baseline keeps ``vs_baseline`` meaningful and is flagged on stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
SHIM_DIR = REPO / "bee_code_interpreter_tpu" / "runtime" / "shim"

N = 32768
ITERS = 16

# The measured payload: a bf16 matmul chain under jit, the shape of work the
# MXU exists for. Chained with a data dependency (no loop hoisting), one
# device->host readback at the end. Written the way a sandbox user writes JAX.
# n=32768 keeps each matmul MXU-bound long enough to amortize loop/dispatch
# overhead (measured 186 TFLOPS = 94% of v5e bf16 peak vs 147 at n=8192); the
# one-time 1/128 pre-scale keeps the chain's magnitudes roughly stable without
# paying a per-iteration epilogue.
TPU_PAYLOAD = f"""
import time
import jax, jax.numpy as jnp
from jax import lax

n, iters = {N}, {ITERS}
if jax.devices()[0].platform == "cpu":
    n, iters = 1024, 4  # no accelerator: validate mechanics only
a = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype=jnp.bfloat16)

@jax.jit
def chain(a):
    a = a * jnp.bfloat16(1 / 128)
    def body(i, x):
        return a @ x
    return lax.fori_loop(0, iters, body, a).sum()

float(chain(a))  # compile + warm
best = float("inf")
for _ in range(3):
    t0 = time.time()
    float(chain(a))
    best = min(best, time.time() - t0)
print(f"RESULT_GFLOPS {{2 * n**3 * iters / best / 1e9:.1f}}")
"""

# Host-CPU baseline: the same kernel as the TPU chain — one-time 1/128
# pre-scale, then a pure data-dependent matmul chain with a single readback —
# through plain numpy (f32; numpy has no bf16), sized down (self-timed wall
# clock, as the reference's own benchmark payload does). n=2048 is enough to
# saturate the host BLAS; anything larger just risks the driver's clock.
CPU_PAYLOAD = """
import time
import numpy as np

n, iters = 2048, 8
a = np.random.rand(n, n).astype(np.float32) * np.float32(1 / 128)
x = a
t0 = time.time()
for _ in range(iters):
    x = a @ x
s = float(x.sum())
dt = time.time() - t0
print(f"RESULT_GFLOPS {2 * n**3 * iters / dt / 1e9:.1f}")
"""

# Live-CPU-baseline fallback: the same payload measured out-of-band on this
# machine class (round-1 verification run: 120 GFLOPS through the identical
# LocalCodeExecutor path). Used only if the live baseline fails; stderr says so.
RECORDED_CPU_GFLOPS = 120.0

LATENCY_PAYLOAD = "print(21 * 2)"


async def run_payload(
    source: str, env: dict[str, str], timeout_s: float
) -> float:
    from bee_code_interpreter_tpu.services.local_code_executor import (
        LocalCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = tempfile.mkdtemp(prefix="bench-")
    executor = LocalCodeExecutor(
        storage=Storage(Path(tmp) / "objects"),
        workspace_root=Path(tmp) / "ws",
        disable_dep_install=True,
        execution_timeout_s=timeout_s,
        shim_dir=SHIM_DIR,
    )
    result = await executor.execute(source, env=env)
    if result.exit_code != 0:
        print(result.stderr, file=sys.stderr)
        raise RuntimeError(f"payload failed (exit {result.exit_code})")
    for line in result.stdout.splitlines():
        if line.startswith("RESULT_GFLOPS"):
            return float(line.split()[1])
    raise RuntimeError(f"no result in stdout: {result.stdout!r}")


def scrub_tunnel_vars() -> None:
    """Drop accelerator-tunnel plugin vars from THIS process (inherited by the
    executor's TPU_PASSTHROUGH_PREFIXES) so CPU-pinned payloads cannot be
    hijacked into a blocking TPU backend init. Called only after the TPU
    measurement — which needs those very vars — has completed."""
    from bee_code_interpreter_tpu.utils.envscrub import scrub_tunnel_plugin_vars

    scrub_tunnel_plugin_vars()


def ensure_native_binary() -> Path | None:
    """Build the C++ executor if needed — synchronously, OUTSIDE any event
    loop (a blocking subprocess inside a coroutine would stall the loop and
    defeat the asyncio.wait_for guard around the latency measurement)."""
    binary = REPO / "executor" / "build" / "executor-server"
    if binary.exists():
        return binary
    try:
        build = subprocess.run(
            ["make", "-C", str(REPO / "executor"), "-s"],
            capture_output=True,
            timeout=180,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"latency: executor build failed ({e})", file=sys.stderr)
        return None
    if build.returncode != 0 or not binary.exists():
        print("latency: no native executor binary", file=sys.stderr)
        return None
    return binary


async def measure_warm_latency_p50_ms(binary: Path, n: int = 20) -> float | None:
    """p50 of a trivial execute through the warm native-executor pool
    (BASELINE.md north-star #3; scripts/measure-latency.py is the full
    percentile harness)."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="bench-lat-"))
    config = Config(
        file_storage_path=str(tmp / "objects"),
        local_workspace_root=str(tmp / "ws"),
        executor_pod_queue_target_length=4,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=Storage(tmp / "objects"), config=config, binary=binary
    )
    try:
        await executor.fill_sandbox_queue()
        samples = []
        for _ in range(n):
            t0 = time.perf_counter()
            result = await executor.execute(LATENCY_PAYLOAD)
            if result.stdout != "42\n":
                raise RuntimeError(f"latency payload failed: {result.stderr}")
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples) * 1000
    finally:
        executor.shutdown()


def main() -> None:
    # --- 1. the headline TPU number (runs first; ambient accelerator env —
    # including any tunnel plugin vars — flows through the executor's
    # passthrough so the payload sees the real chip) -----------------------
    # Budgets sized so the worst case (wedged tunnel: TPU payload burns its
    # full timeout) still leaves room for the CPU + latency measurements
    # inside a ~600 s driver window. A healthy chip needs ~90 s (init ~20-40,
    # compile ~20-40, 4 timed chains ~25).
    tpu_gflops: float | None = None
    try:
        tpu_gflops = asyncio.run(run_payload(TPU_PAYLOAD, {}, timeout_s=300.0))
        print(f"tpu: {tpu_gflops:.1f} GFLOPS", file=sys.stderr)
    except Exception as e:
        print(f"tpu payload failed: {e}", file=sys.stderr)

    # --- 2. CPU baseline (guarded: can only degrade vs_baseline) ----------
    scrub_tunnel_vars()
    cpu_gflops: float | None = None
    cpu_source = "measured"
    try:
        cpu_gflops = asyncio.run(
            run_payload(
                CPU_PAYLOAD,
                {"JAX_PLATFORMS": "cpu", "BCI_XLA_REROUTE": "0"},
                timeout_s=90.0,
            )
        )
        print(f"cpu baseline: {cpu_gflops:.1f} GFLOPS", file=sys.stderr)
    except Exception as e:
        print(
            f"cpu baseline failed ({e}); using recorded "
            f"{RECORDED_CPU_GFLOPS} GFLOPS",
            file=sys.stderr,
        )
        cpu_gflops = RECORDED_CPU_GFLOPS
        cpu_source = "recorded"

    # --- 3. warm-pool execute latency (guarded; extra field) --------------
    latency_p50_ms: float | None = None
    binary = ensure_native_binary()
    if binary is not None:
        try:
            latency_p50_ms = asyncio.run(
                asyncio.wait_for(measure_warm_latency_p50_ms(binary), timeout=90.0)
            )
            if latency_p50_ms is not None:
                print(f"warm execute p50: {latency_p50_ms:.1f} ms", file=sys.stderr)
        except Exception as e:
            print(f"latency measurement failed: {e}", file=sys.stderr)

    if tpu_gflops is not None:
        result = {
            "metric": "dense matmul GFLOPS/chip via /v1/execute (bf16 32768^3 jit chain)",
            "value": round(tpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": round(tpu_gflops / cpu_gflops, 2),
        }
    else:  # no chip reachable: report the CPU path honestly
        result = {
            "metric": "dense matmul GFLOPS via /v1/execute (CPU fallback - no TPU reachable)",
            "value": round(cpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": 1.0,
        }
    result["latency_warm_p50_ms"] = (
        round(latency_p50_ms, 1) if latency_p50_ms is not None else None
    )
    result["cpu_baseline_gflops"] = round(cpu_gflops, 1)
    # "recorded" = the live CPU run failed and vs_baseline uses the recorded
    # machine-class figure — a constant must never masquerade as a measurement
    result["cpu_baseline_source"] = cpu_source
    print(json.dumps(result))


if __name__ == "__main__":
    main()
