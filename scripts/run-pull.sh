#!/usr/bin/env bash
# Deploy from the published images instead of a local build (reference
# scripts/run-pull.sh:16-24 behavior). CI publishes to
# ghcr.io/<owner>/<repo>/{service,executor}:{<tag>,latest}; point IMAGE_REPO at
# that prefix (k8s/tpu.yaml carries an IMAGE_REPO placeholder).
set -euo pipefail
cd "$(dirname "$0")/.."

: "${IMAGE_REPO:?set IMAGE_REPO to the registry prefix, e.g. ghcr.io/<owner>/<repo>}"

kubectl delete pod bee-code-interpreter-tpu --ignore-not-found=true --wait=true
sed "s#IMAGE_REPO#${IMAGE_REPO}#g" k8s/tpu.yaml | kubectl apply -f -
kubectl wait --for=condition=Ready pod/bee-code-interpreter-tpu --timeout=300s

kubectl port-forward pod/bee-code-interpreter-tpu 50081:50081 50051:50051 &
trap 'kill %1' EXIT
kubectl logs -f bee-code-interpreter-tpu
