#!/usr/bin/env bash
# Deploy from the published images instead of a local build (reference
# scripts/run-pull.sh:16-24 behavior).
set -euo pipefail
cd "$(dirname "$0")/.."

kubectl delete pod bee-code-interpreter-tpu --ignore-not-found=true --wait=true
kubectl apply -f k8s/tpu.yaml
kubectl wait --for=condition=Ready pod/bee-code-interpreter-tpu --timeout=300s

kubectl port-forward pod/bee-code-interpreter-tpu 50081:50081 50051:50051 &
trap 'kill %1' EXIT
kubectl logs -f bee-code-interpreter-tpu
