#!/usr/bin/env python
"""Run + time the Pallas flash-attention kernels on the real TPU chip.

CI exercises the kernels in Pallas interpreter mode only; this script is the
hardware proof: Mosaic-lowers the forward AND backward kernels on the
attached chip, checks numerics against the jax reference, and reports
achieved TFLOPS vs XLA's own fused attention.

Usage:  python scripts/bench-flash-attention.py  (needs a reachable TPU)
Prints one JSON line per case; exits 2 if no TPU.
"""

from __future__ import annotations

import functools
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.ops.flash_attention import flash_attention
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention


def attention_flops(B: int, H: int, L: int, D: int, causal: bool) -> float:
    # QK^T and PV: 2 matmuls of 2*B*H*L*L*D flops each; causal halves
    flops = 2 * 2 * B * H * L * L * D
    return flops / 2 if causal else flops


def timed_scalar(fn, q, k, v, iters: int = 4) -> float:
    """Per-call seconds with a scalar host readback per call.

    block_until_ready is not a reliable completion barrier through a TPU
    tunnel (measured: apparent PFLOPS); a device→host readback is. ``fn``
    must return a scalar. Per-call readback latency (~ms) is noise next to
    the multi-ms attention calls being measured.
    """
    jit_fn = jax.jit(fn)
    float(jit_fn(q, k, v))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            float(jit_fn(q, k, v))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main() -> None:
    # Bounded out-of-process probe (bench.py's): a wedged tunnel must produce
    # the exit-2 diagnostic, not hang this process on jax.devices().
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    probe = bench.probe_tpu()
    if not probe.get("ok") or probe.get("platform") != "tpu":
        print(f"no TPU: {probe}", file=sys.stderr)
        sys.exit(2)

    B, H, L, D = 4, 16, 4096, 128
    causal = True
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (B, H, L, D), dtype=jnp.bfloat16)
        for i in range(3)
    )

    # --- correctness on hardware (fwd + bwd Mosaic lowering) -------------
    small = tuple(
        jax.random.normal(jax.random.PRNGKey(i), (1, 2, 512, 64), dtype=jnp.bfloat16)
        for i in range(3)
    )
    out_hw = flash_attention(*small, causal, None, 256, 256, False)
    out_ref = reference_attention(*small, causal=True)
    fwd_err = float(jnp.max(jnp.abs(out_hw.astype(jnp.float32) - out_ref.astype(jnp.float32))))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, None, 512, 512, False) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=causal) ** 2).sum()

    g_hw = jax.grad(loss_flash, argnums=(0, 1, 2))(*small)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(*small)
    bwd_err = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(g_hw, g_ref)
    )
    # bf16 tolerance: values are O(sqrt(D)) after softmax-weighted sums
    assert fwd_err < 0.1, f"forward kernel diverges on hardware: {fwd_err}"
    assert bwd_err < 1.0, f"backward kernel diverges on hardware: {bwd_err}"
    print(
        json.dumps({"case": "hardware_numerics", "fwd_max_err": round(fwd_err, 4),
                    "bwd_max_err": round(bwd_err, 4)})
    )

    # --- forward throughput ----------------------------------------------
    flops = attention_flops(B, H, L, D, causal)
    if "--sweep" in sys.argv:
        for bq, bk in [(256, 256), (512, 512), (512, 1024), (1024, 512),
                       (1024, 1024), (1024, 2048)]:
            t = timed_scalar(
                lambda x, k, v, bq=bq, bk=bk: flash_attention(
                    x, k, v, causal, None, bq, bk, False
                ).astype(jnp.float32).sum(),
                q, k, v,
            )
            print(json.dumps({
                "case": "forward_sweep", "block_q": bq, "block_k": bk,
                "tflops": round(flops / t / 1e12, 1),
            }))
    t_flash = timed_scalar(
        lambda x, k, v: flash_attention(
            x, k, v, causal, None, 1024, 1024, False
        ).astype(jnp.float32).sum(),
        q, k, v,
    )
    t_xla = timed_scalar(
        lambda x, k, v: reference_attention(x, k, v, causal=causal)
        .astype(jnp.float32).sum(),
        q, k, v,
    )
    print(
        json.dumps(
            {
                "case": "forward",
                "shape": [B, H, L, D],
                "flash_tflops": round(flops / t_flash / 1e12, 1),
                "xla_ref_tflops": round(flops / t_xla / 1e12, 1),
                "speedup_vs_xla": round(t_xla / t_flash, 2),
            }
        )
    )

    # --- train-step (fwd+bwd) throughput (~3x fwd flops) ------------------
    # All three grads on BOTH sides: with argnums=0 alone, XLA prunes the
    # dk/dv computation at transpose time while the opaque custom_vjp kernel
    # always computes all three — a skewed comparison.
    def grad_sum(loss):
        def fn(x, k, v):
            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(x, k, v)
            return (
                dq.astype(jnp.float32).sum()
                + dk.astype(jnp.float32).sum()
                + dv.astype(jnp.float32).sum()
            )
        return fn

    t_gflash = timed_scalar(grad_sum(loss_flash), q, k, v)
    t_gref = timed_scalar(grad_sum(loss_ref), q, k, v)
    print(
        json.dumps(
            {
                "case": "forward+backward",
                "shape": [B, H, L, D],
                "flash_tflops": round(3 * flops / t_gflash / 1e12, 1),
                "xla_ref_tflops": round(3 * flops / t_gref / 1e12, 1),
                "speedup_vs_xla": round(t_gref / t_gflash, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
