#!/usr/bin/env python
"""Run + time the Pallas flash-attention kernels on the real TPU chip.

CI exercises the kernels in Pallas interpreter mode only; this script is the
hardware proof: Mosaic-lowers the forward AND backward kernels on the
attached chip, checks numerics against the jax reference, and reports
achieved TFLOPS against two baselines — the XLA-compiled reference
attention (naive einsum+softmax) and ``jax.nn.dot_product_attention``
(the library's own fused entry point) — plus the grouped-query (GQA)
cases where the kernels read the compact KV heads directly. Successful
measurements are appended to the TPU_EVIDENCE.jsonl ledger.

Timing method: N data-dependent kernel applications chained inside ONE jit
(the output feeds the next call's query), a single scalar readback at the
end. Per-call device→host readbacks are NOT a usable clock here — a tunnel
round-trip measured ~70 ms this session, swamping ~10 ms kernels — and
block_until_ready is not a reliable barrier through the tunnel at all
(measured: apparent PFLOPS).

Usage:  python scripts/bench-flash-attention.py  [--sweep]
Prints one JSON line per case; exits 2 if no TPU.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
from jax import lax

from bee_code_interpreter_tpu.ops.flash_attention import flash_attention
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention


def attention_flops(B: int, H: int, L: int, D: int, causal: bool) -> float:
    # QK^T and PV: 2 matmuls of 2*B*H*L*L*D flops each; causal halves
    flops = 2 * 2 * B * H * L * L * D
    return flops / 2 if causal else flops


def _best_of(f, q, k, v, reps: int = 3) -> float:
    float(f(q, k, v))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return best


def _timed_chain(make_f, q, k, v, n_chain: int) -> float:
    """Per-call seconds from the difference of an n_chain-long and a 1-long
    chain: (t_N - t_1) / (N - 1) cancels the per-measurement fixed cost —
    dispatch plus the readback RTT, which would otherwise add RTT/N to every
    call (~9 ms at the ~70 ms RTT measured through the tunnel this session,
    not negligible against ~10 ms kernels). Difference + sanity guard live
    in utils/benchclock.chain_diff (shared with bench-decode and bench.py's
    flash payload)."""
    from bee_code_interpreter_tpu.utils.benchclock import chain_diff

    t_n = _best_of(make_f(n_chain), q, k, v)
    t_1 = _best_of(make_f(1), q, k, v)
    return chain_diff(t_n, t_1, n_chain)


def timed_fwd(attn, q, k, v, n_chain: int = 8) -> float:
    """Per-call seconds for ``attn(q, k, v) -> [B, H, L, D]``: the output is
    the next call's query, so the chain cannot be reordered or elided."""

    def make_f(length):
        @jax.jit
        def f(q, k, v):
            def body(c, _):
                return attn(c, k, v), None

            c, _ = lax.scan(body, q, None, length=length)
            return c.astype(jnp.float32).sum()

        return f

    return _timed_chain(make_f, q, k, v, n_chain)


def timed_fwd_bwd(loss, q, k, v, n_chain: int = 8) -> float:
    """Per-call seconds for one value_and_grad of ``loss`` wrt (q, k, v):
    chained as gradient-descent steps on all three operands, so dq, dk AND
    dv are all live (grad wrt q alone would let XLA prune the dk/dv work —
    a skewed comparison against the opaque custom_vjp kernel, which always
    computes all three)."""

    def make_f(length):
        @jax.jit
        def f(q, k, v):
            def body(carry, _):
                q, k, v = carry
                dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
                s = 1e-3
                return (
                    (q - s * dq).astype(q.dtype),
                    (k - s * dk.astype(jnp.float32)).astype(k.dtype),
                    (v - s * dv.astype(jnp.float32)).astype(v.dtype),
                ), None

            (q, _, _), _ = lax.scan(body, (q, k, v), None, length=length)
            return q.astype(jnp.float32).sum()

        return f

    return _timed_chain(make_f, q, k, v, n_chain)


def run_measurements(emit, sweep: bool = False) -> None:
    """Every hardware measurement, run inside an ALREADY-initialized jax
    process. Factored out of main() so scripts/tpu-oneshot.py can run the
    whole battery as ONE tunnel client: the tunnel serves (at best) one
    client per healthy window, so the probe-then-measure-in-a-new-process
    pattern is exactly how previous rounds lost their windows."""
    causal = True

    # --- correctness on hardware (fwd + bwd Mosaic lowering) -------------
    small = tuple(
        jax.random.normal(jax.random.PRNGKey(i), (1, 2, 512, 64), dtype=jnp.bfloat16)
        for i in range(3)
    )
    out_hw = flash_attention(*small, causal, None, 256, 256, False)
    out_ref = reference_attention(*small, causal=True)
    fwd_err = float(jnp.max(jnp.abs(out_hw.astype(jnp.float32) - out_ref.astype(jnp.float32))))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, None, 512, 512, False) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=causal) ** 2).sum()

    g_hw = jax.grad(loss_flash, argnums=(0, 1, 2))(*small)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(*small)
    bwd_err = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(g_hw, g_ref)
    )
    # bf16 tolerance: values are O(sqrt(D)) after softmax-weighted sums
    assert fwd_err < 0.1, f"forward kernel diverges on hardware: {fwd_err}"
    assert bwd_err < 1.0, f"backward kernel diverges on hardware: {bwd_err}"

    # GQA on silicon: compact KV vs the broadcast reference
    qg = jax.random.normal(jax.random.PRNGKey(7), (1, 8, 512, 64), jnp.bfloat16)
    kg, vg = (
        jax.random.normal(jax.random.PRNGKey(8 + i), (1, 2, 512, 64), jnp.bfloat16)
        for i in range(2)
    )
    out_gqa = flash_attention(qg, kg, vg, causal, None, 256, 256, False)
    ref_gqa = reference_attention(
        qg, jnp.repeat(kg, 4, 1), jnp.repeat(vg, 4, 1), causal=True
    )
    gqa_err = float(
        jnp.max(jnp.abs(out_gqa.astype(jnp.float32) - ref_gqa.astype(jnp.float32)))
    )
    assert gqa_err < 0.1, f"GQA forward diverges on hardware: {gqa_err}"
    emit("hardware_numerics", {"fwd_max_err": round(fwd_err, 4),
                               "bwd_max_err": round(bwd_err, 4),
                               "gqa_fwd_max_err": round(gqa_err, 4)})

    # --- forward throughput (MHA) ----------------------------------------
    B, H, L, D = 4, 16, 4096, 128
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (B, H, L, D), dtype=jnp.bfloat16)
        for i in range(3)
    )
    flops = attention_flops(B, H, L, D, causal)
    if sweep:
        for bq, bk in [(256, 256), (512, 512), (512, 1024), (1024, 512),
                       (1024, 1024), (1024, 2048)]:
            t = timed_fwd(
                lambda x, k, v, bq=bq, bk=bk: flash_attention(
                    x, k, v, causal, None, bq, bk, False
                ),
                q, k, v,
            )
            print(json.dumps({
                "case": "forward_sweep", "block_q": bq, "block_k": bk,
                "tflops": round(flops / t / 1e12, 1),
            }))
    t_flash = timed_fwd(
        lambda x, k, v: flash_attention(x, k, v, causal, None, 1024, 1024, False),
        q, k, v,
    )
    t_xla = timed_fwd(
        lambda x, k, v: reference_attention(x, k, v, causal=causal).astype(x.dtype),
        q, k, v,
    )
    # Honest fused baseline (ADVICE r3 #3): jax.nn.dot_product_attention is
    # the library's own attention entry point — whatever fused lowering XLA
    # ships is what a user gets without our kernel. It wants BTNH layout, so
    # it is timed natively in that layout (no transpose tax in its chain);
    # the flop count is identical.
    qT, kT, vT = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    t_dpa = timed_fwd(
        lambda x, k, v: jax.nn.dot_product_attention(x, k, v, is_causal=True),
        qT, kT, vT,
    )
    emit("forward", {
        "shape": [B, H, L, D],
        "flash_tflops": round(flops / t_flash / 1e12, 1),
        "xla_ref_tflops": round(flops / t_xla / 1e12, 1),
        "jax_dpa_tflops": round(flops / t_dpa / 1e12, 1),
        "speedup_vs_xla_ref": round(t_xla / t_flash, 2),
        "speedup_vs_jax_dpa": round(t_dpa / t_flash, 2),
    })

    # --- forward throughput (GQA, llama3-8b head geometry) ----------------
    KVH = 8
    Bg, Hg = 4, 32
    qG = jax.random.normal(jax.random.PRNGKey(10), (Bg, Hg, L, D), jnp.bfloat16)
    kG, vG = (
        jax.random.normal(jax.random.PRNGKey(11 + i), (Bg, KVH, L, D), jnp.bfloat16)
        for i in range(2)
    )
    flops_g = attention_flops(Bg, Hg, L, D, causal)
    t_gqa = timed_fwd(lambda x, k, v: flash_attention(x, k, v, causal), qG, kG, vG)
    t_rep = timed_fwd(
        lambda x, k, v: flash_attention(
            x, jnp.repeat(k, Hg // KVH, 1), jnp.repeat(v, Hg // KVH, 1), causal
        ),
        qG, kG, vG,
    )
    emit("forward_gqa", {
        "shape": [Bg, Hg, L, D], "kv_heads": KVH,
        "gqa_native_tflops": round(flops_g / t_gqa / 1e12, 1),
        "repeat_kv_tflops": round(flops_g / t_rep / 1e12, 1),
        "speedup_vs_repeat": round(t_rep / t_gqa, 2),
    })

    # --- train-step (fwd+bwd) throughput (~3x fwd flops) ------------------
    t_gflash = timed_fwd_bwd(loss_flash, q, k, v)
    t_gref = timed_fwd_bwd(loss_ref, q, k, v)
    emit("forward+backward", {
        "shape": [B, H, L, D],
        "flash_tflops": round(3 * flops / t_gflash / 1e12, 1),
        "xla_ref_tflops": round(3 * flops / t_gref / 1e12, 1),
        "speedup_vs_xla_ref": round(t_gref / t_gflash, 2),
    })

    def loss_gqa(q, k, v):
        return (flash_attention(q, k, v, causal).astype(jnp.float32) ** 2).sum()

    t_ggqa = timed_fwd_bwd(loss_gqa, qG, kG, vG, n_chain=4)
    emit("forward+backward_gqa", {
        "shape": [Bg, Hg, L, D], "kv_heads": KVH,
        "gqa_native_tflops": round(3 * flops_g / t_ggqa / 1e12, 1),
    })


def main() -> None:
    # Bounded out-of-process probe (bench.py's): a wedged tunnel must produce
    # the exit-2 diagnostic, not hang this process on jax.devices().
    import functools
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    probe = bench.probe_tpu()
    if not probe.get("ok") or probe.get("platform") != "tpu":
        print(f"no TPU: {probe}", file=sys.stderr)
        sys.exit(2)

    from bee_code_interpreter_tpu.utils import evidence

    run_measurements(
        functools.partial(
            evidence.emit, script="scripts/bench-flash-attention.py"
        ),
        sweep="--sweep" in sys.argv,
    )


if __name__ == "__main__":
    main()
