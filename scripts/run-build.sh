#!/usr/bin/env bash
# Build both images locally, (re)deploy to the current kubectl context, wait
# for readiness, port-forward, and follow logs (reference scripts/run-build.sh
# :16-27 behavior).
set -euo pipefail
cd "$(dirname "$0")/.."

docker build -t bee-code-interpreter-tpu:local .
docker build -t bee-code-interpreter-tpu-executor:local \
  --build-context repo=. executor/

kubectl delete pod bee-code-interpreter-tpu --ignore-not-found=true --wait=true
kubectl apply -f k8s/local.yaml
kubectl wait --for=condition=Ready pod/bee-code-interpreter-tpu --timeout=120s

kubectl port-forward pod/bee-code-interpreter-tpu 50081:50081 50051:50051 &
trap 'kill %1' EXIT
kubectl logs -f bee-code-interpreter-tpu
