#!/usr/bin/env python3
"""Runbook-ready text rendering of ``GET /v1/slo`` (docs/observability.md).

Per declared objective: the error budget remaining, a table of the four
burn-rate windows (5m/30m/1h/6h), and the state of both multi-window alert
pairs — the numbers an on-call pastes into an incident doc.

    python scripts/slo-report.py [--url http://localhost:50081]

Pointed at a ROUTER edge, the same endpoint answers the federated
document (docs/capacity.md): the user-perceived numbers at top level plus
every replica's own budget under ``fleet`` — rendered as a per-replica
roll-call with the names that failed to answer called out.

Exit codes: 0 quiet, 1 unreachable, 3 a slow (ticket) alert firing,
4 a fast (page) alert firing — fleet-wide rollups included, so a single
replica paging fails a deploy gate even while the edge looks clean.
"""

from __future__ import annotations

import argparse
import sys

import httpx


def render(slo: dict) -> str:
    objectives = slo.get("objectives") or []
    if not objectives:
        return "no SLO objectives declared (set APP_SLO_AVAILABILITY / APP_SLO_LATENCY_MS)"
    lines: list[str] = []
    for o in objectives:
        title = f"objective {o['name']} — target {o['target'] * 100:g}%"
        if o.get("threshold_ms") is not None:
            title += f" within {o['threshold_ms']:g}ms"
        lines.append(title)
        lines.append(
            f"  error budget remaining (6h window): "
            f"{o['error_budget_remaining_ratio']:.1%}"
        )
        header = f"  {'WINDOW':<8} {'TOTAL':>8} {'BAD':>6} {'BAD%':>8} {'BURN':>8}"
        lines.append(header)
        lines.append("  " + "-" * (len(header) - 2))
        for window in ("5m", "30m", "1h", "6h"):
            w = o["windows"][window]
            lines.append(
                f"  {window:<8} {w['total']:>8} {w['bad']:>6} "
                f"{w['bad_ratio']:>8.2%} {w['burn_rate']:>8.2f}"
            )
        for alert in o["alerts"]:
            state = "FIRING" if alert["firing"] else "ok"
            lines.append(
                f"  alert[{alert['severity']}] "
                f"{'&'.join(alert['windows'])} > {alert['burn_threshold']:g}x: "
                f"{state} (short={alert['short_burn_rate']:.2f} "
                f"long={alert['long_burn_rate']:.2f})"
            )
        lines.append("")
    return "\n".join(lines).rstrip()


def render_fleet(slo: dict) -> str | None:
    """The federated sections a router edge adds — None on a plain
    replica document, so the replica rendering is unchanged."""
    fleet = slo.get("fleet")
    if fleet is None:
        return None
    lines = ["fleet (per-replica error budgets)"]
    for name in sorted(fleet):
        doc = fleet[name] or {}
        objectives = doc.get("objectives") or []
        if objectives:
            budget = min(
                o.get("error_budget_remaining_ratio", 1.0)
                for o in objectives
            )
            budget_s = f"budget {budget:.1%}"
        else:
            budget_s = "no objectives"
        state = (
            "FAST-BURN"
            if doc.get("fast_burn_alerting")
            else "alerting"
            if doc.get("alerting")
            else "ok"
        )
        lines.append(f"  {name:<12} {budget_s:<16} {state}")
    failed = slo.get("replicas_failed") or {}
    for name in sorted(failed):
        lines.append(f"  {name:<12} {'NO ANSWER':<16} {failed[name]}")
    lines.append(
        f"  fleet_alerting={slo.get('fleet_alerting')} "
        f"fleet_fast_burn={slo.get('fleet_fast_burn')} "
        f"reporting={len(slo.get('replicas_reporting') or [])}"
    )
    return "\n".join(lines)


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render GET /v1/slo burn-rate windows as a text table."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    args = parser.parse_args()
    base = args.url.rstrip("/")
    try:
        with httpx.Client(timeout=10.0) as client:
            slo = client.get(f"{base}/v1/slo").raise_for_status().json()
    except httpx.HTTPError as e:
        print(f"slo-report: cannot reach {base}: {e}", file=sys.stderr)
        return 1
    print(render(slo))
    fleet = render_fleet(slo)
    if fleet is not None:
        print()
        print(fleet)
    if slo.get("fast_burn_alerting") or slo.get("fleet_fast_burn"):
        return 4
    if slo.get("alerting") or slo.get("fleet_alerting"):
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
