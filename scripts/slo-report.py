#!/usr/bin/env python3
"""Runbook-ready text rendering of ``GET /v1/slo`` (docs/observability.md).

Per declared objective: the error budget remaining, a table of the four
burn-rate windows (5m/30m/1h/6h), and the state of both multi-window alert
pairs — the numbers an on-call pastes into an incident doc.

    python scripts/slo-report.py [--url http://localhost:50081]

Exit codes: 0 quiet, 1 unreachable, 3 a slow (ticket) alert firing,
4 a fast (page) alert firing — scriptable from deploy gates.
"""

from __future__ import annotations

import argparse
import sys

import httpx


def render(slo: dict) -> str:
    objectives = slo.get("objectives") or []
    if not objectives:
        return "no SLO objectives declared (set APP_SLO_AVAILABILITY / APP_SLO_LATENCY_MS)"
    lines: list[str] = []
    for o in objectives:
        title = f"objective {o['name']} — target {o['target'] * 100:g}%"
        if o.get("threshold_ms") is not None:
            title += f" within {o['threshold_ms']:g}ms"
        lines.append(title)
        lines.append(
            f"  error budget remaining (6h window): "
            f"{o['error_budget_remaining_ratio']:.1%}"
        )
        header = f"  {'WINDOW':<8} {'TOTAL':>8} {'BAD':>6} {'BAD%':>8} {'BURN':>8}"
        lines.append(header)
        lines.append("  " + "-" * (len(header) - 2))
        for window in ("5m", "30m", "1h", "6h"):
            w = o["windows"][window]
            lines.append(
                f"  {window:<8} {w['total']:>8} {w['bad']:>6} "
                f"{w['bad_ratio']:>8.2%} {w['burn_rate']:>8.2f}"
            )
        for alert in o["alerts"]:
            state = "FIRING" if alert["firing"] else "ok"
            lines.append(
                f"  alert[{alert['severity']}] "
                f"{'&'.join(alert['windows'])} > {alert['burn_threshold']:g}x: "
                f"{state} (short={alert['short_burn_rate']:.2f} "
                f"long={alert['long_burn_rate']:.2f})"
            )
        lines.append("")
    return "\n".join(lines).rstrip()


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render GET /v1/slo burn-rate windows as a text table."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    args = parser.parse_args()
    base = args.url.rstrip("/")
    try:
        with httpx.Client(timeout=10.0) as client:
            slo = client.get(f"{base}/v1/slo").raise_for_status().json()
    except httpx.HTTPError as e:
        print(f"slo-report: cannot reach {base}: {e}", file=sys.stderr)
        return 1
    print(render(slo))
    if slo.get("fast_burn_alerting"):
        return 4
    if slo.get("alerting"):
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
