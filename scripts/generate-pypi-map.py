#!/usr/bin/env python
"""Regenerate executor/pypi_map.tsv from runtime/dep_guess.py's PYPI_MAP.

The Python guesser (unit-test oracle) and the C++ server (executor/
dep_guess.hpp loading /pypi_map.tsv) must agree on the import→distribution
table; this script is the one direction of truth flow. Run after editing
PYPI_MAP.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bee_code_interpreter_tpu.runtime.dep_guess import PYPI_MAP  # noqa: E402

OUT = REPO / "executor" / "pypi_map.tsv"


def harvest() -> None:
    """Print import→dist rows mined from the *installed* environment's
    package metadata (top_level.txt / RECORD) where the import name differs
    from the distribution name — candidates for PYPI_MAP, to be reviewed by
    hand (metadata contains junk like `examples` or `docs` top-levels)."""
    import importlib.metadata as md

    from bee_code_interpreter_tpu.runtime.dep_guess import _normalize as norm

    rows: dict[str, str] = {}
    for dist in md.distributions():
        name = dist.metadata["Name"]
        if not name:
            continue
        tops: set[str] = set()
        try:
            top_txt = dist.read_text("top_level.txt")
            if top_txt:
                tops.update(t.strip() for t in top_txt.splitlines() if t.strip())
        except Exception:
            pass
        if not tops and dist.files:
            for f in dist.files:
                top = f.parts[0]
                if top.endswith(".py"):
                    top = top[:-3]
                if top.isidentifier():
                    tops.add(top)
        for top in tops:
            if top.startswith("_") or not top.isidentifier():
                continue
            if norm(top) != norm(name):
                rows[top] = name
    for imp in sorted(rows):
        print(f"{imp}\t{rows[imp]}")
    print(f"# {len(rows)} candidate rows (review before merging)", file=sys.stderr)


def main() -> None:
    if "--harvest" in sys.argv:
        harvest()
        return
    lines = [
        "# import-name -> PyPI distribution name "
        "(generated from runtime/dep_guess.py PYPI_MAP "
        "by scripts/generate-pypi-map.py)"
    ]
    lines += [f"{imp}\t{dist}" for imp, dist in sorted(PYPI_MAP.items())]
    OUT.write_text("\n".join(lines) + "\n")
    print(f"wrote {len(PYPI_MAP)} entries to {OUT}")


if __name__ == "__main__":
    main()
