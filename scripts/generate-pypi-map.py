#!/usr/bin/env python
"""Regenerate executor/pypi_map.tsv from runtime/dep_guess.py's PYPI_MAP.

The Python guesser (unit-test oracle) and the C++ server (executor/
dep_guess.hpp loading /pypi_map.tsv) must agree on the import→distribution
table; this script is the one direction of truth flow. Run after editing
PYPI_MAP.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bee_code_interpreter_tpu.runtime.dep_guess import PYPI_MAP  # noqa: E402

OUT = REPO / "executor" / "pypi_map.tsv"


def main() -> None:
    lines = [
        "# import-name -> PyPI distribution name "
        "(generated from runtime/dep_guess.py PYPI_MAP "
        "by scripts/generate-pypi-map.py)"
    ]
    lines += [f"{imp}\t{dist}" for imp, dist in sorted(PYPI_MAP.items())]
    OUT.write_text("\n".join(lines) + "\n")
    print(f"wrote {len(PYPI_MAP)} entries to {OUT}")


if __name__ == "__main__":
    main()
