#!/usr/bin/env python
"""Validate TransformerConfig.llama3_8b() at BASELINE topology on virtual devices.

Lowers (does NOT compile or materialize) the full train step and the cached
decode step for the 8B flagship config over a 64-virtual-CPU-device mesh —
the v5e-64 shape from BASELINE.json config #5 — using abstract
ShapeDtypeStructs with real NamedShardings attached. This catches exactly the
class of bug virtual devices exist for (axis-divisibility, spec/mesh
factoring, ring-attention layout at scale) without needing 64 chips or 32 GB
of weights (VERDICT r2 weak #4).

Also checks, analytically from param_specs, that per-device param + AdamW
state bytes fit v5e HBM (16 GiB).

Run under:
    XLA_FLAGS=--xla_force_host_platform_device_count=64 JAX_PLATFORMS=cpu \
        python scripts/validate-llama3-topology.py

Prints one JSON line per validated case; exits nonzero on any failure.
"""

from __future__ import annotations

import json
import math
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Same hazard as __graft_entry__._force_virtual_cpu_devices: ambient
# accelerator-tunnel plugin vars hook jax backend init even under
# JAX_PLATFORMS=cpu, and the dev box prepends its platform to jax_platforms
# regardless of the env var. Scrub + force-config before the first backend
# touch (mirrors tests/conftest.py).
import os  # noqa: E402

from bee_code_interpreter_tpu.utils.envscrub import (  # noqa: E402
    scrub_tunnel_plugin_vars,
)

scrub_tunnel_plugin_vars()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from bee_code_interpreter_tpu.models import transformer as T  # noqa: E402

HBM_BYTES = 16 * 1024**3  # v5e per-chip HBM
N_DEVICES = 64


def build_mesh(axes: dict[str, int]) -> Mesh:
    devices = np.array(jax.devices()[:N_DEVICES]).reshape(*axes.values())
    return Mesh(devices, tuple(axes))


def shard_factor(spec: P, mesh: Mesh) -> int:
    factor = 1
    for entry in spec:
        if entry is None:
            continue
        for ax in entry if isinstance(entry, tuple) else (entry,):
            factor *= mesh.shape[ax]
    return factor


def attach_shardings(shapes, specs, mesh: Mesh):
    def attach(sds, spec):
        # Divisibility is enforced here: an axis that doesn't split evenly
        # over its mesh axes raises at ShapeDtypeStruct/sharding creation or
        # at lower() — the bug class this script exists to catch.
        return jax.ShapeDtypeStruct(
            sds.shape, sds.dtype, sharding=NamedSharding(mesh, spec)
        )

    return jax.tree.map(attach, shapes, specs)


def per_device_state_bytes(config, mesh: Mesh, with_optimizer: bool) -> int:
    params_shape = jax.eval_shape(
        lambda k: T.init_params(config, k), jax.random.PRNGKey(0)
    )
    specs = T.param_specs(config, mesh)
    total = 0
    for sds, spec in zip(jax.tree.leaves(params_shape), jax.tree.leaves(specs)):
        leaf_bytes = math.prod(sds.shape) * sds.dtype.itemsize
        per_dev = leaf_bytes // shard_factor(spec, mesh)
        # f32 master params; AdamW adds same-sharded mu + nu (3x); apply-time
        # bf16 cast adds a transient 0.5x
        total += per_dev * (3 if with_optimizer else 1)
    return total


def validate_train(
    axes: dict[str, int], config=None, case: str = "train"
) -> dict:
    mesh = build_mesh(axes)
    config = config or T.TransformerConfig.llama3_8b()
    model = T.Transformer(config, mesh)

    params_shape = jax.eval_shape(
        lambda k: T.init_params(config, k), jax.random.PRNGKey(0)
    )
    specs = T.param_specs(config, mesh)
    params_sds = attach_shardings(params_shape, specs, mesh)

    optimizer = model.make_optimizer()
    opt_sds = jax.eval_shape(optimizer.init, params_shape)

    batch_mult = math.prod(
        mesh.shape[a] for a in ("dp", "fsdp") if a in mesh.axis_names
    )
    B = max(1, batch_mult)
    L = config.max_seq_len
    batch_spec = model.batch_sharding().spec
    batch_sds = {
        "tokens": jax.ShapeDtypeStruct(
            (B, L), jnp.int32, sharding=NamedSharding(mesh, batch_spec)
        ),
        "targets": jax.ShapeDtypeStruct(
            (B, L), jnp.int32, sharding=NamedSharding(mesh, batch_spec)
        ),
    }

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(T.loss_fn)(params, batch, config, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    lowered = jax.jit(train_step).lower(params_sds, opt_sds, batch_sds)
    # Every big param leaf (the matrices; norm scales are deliberately
    # replicated and tiny) must actually shard, not stay replicated
    unsharded = [
        path
        for (path, sds), spec in zip(
            jax.tree.flatten_with_path(params_shape)[0], jax.tree.leaves(specs)
        )
        if math.prod(sds.shape) >= 16 * 2**20 and shard_factor(spec, mesh) == 1
    ]
    assert not unsharded, f"replicated large params: {unsharded}"

    state_bytes = per_device_state_bytes(config, mesh, with_optimizer=True)
    assert state_bytes < HBM_BYTES, (
        f"param+optimizer state {state_bytes/2**30:.2f} GiB/device exceeds "
        f"v5e HBM on mesh {axes}"
    )
    return {
        "case": case,
        "mesh": axes,
        "batch": [B, L],
        "per_device_state_gib": round(state_bytes / 2**30, 2),
        "lowered": bool(lowered.as_text()[:1]),
    }


def validate_decode(axes: dict[str, int]) -> dict:
    mesh = build_mesh(axes)
    config = T.TransformerConfig.llama3_8b()

    params_shape = jax.eval_shape(
        lambda k: T.init_params(config, k), jax.random.PRNGKey(0)
    )
    specs = T.param_specs(config, mesh)
    params_sds = attach_shardings(params_shape, specs, mesh)

    batch_mult = math.prod(
        mesh.shape[a] for a in ("dp", "fsdp") if a in mesh.axis_names
    )
    sp = mesh.shape.get("sp", 1)
    B = max(1, batch_mult)
    L = config.max_seq_len  # long-context prefill: ring attention over sp

    # Prefill: full forward with return_kv (ring attention when sp > 1)
    tokens_sds = jax.ShapeDtypeStruct(
        (B, L),
        jnp.int32,
        sharding=NamedSharding(
            mesh, P(tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None,
                    "sp" if sp > 1 else None)
        ),
    )
    prefill = jax.jit(
        lambda p, t: T.forward(p, t, config, mesh, return_kv=True)
    ).lower(params_sds, tokens_sds)

    # Incremental decode against the cache
    cache_shape = (config.n_layers, B, config.kv_heads, L + 64, config.head_dim)
    cache_sds = {
        "k": jax.ShapeDtypeStruct(cache_shape, config.dtype),
        "v": jax.ShapeDtypeStruct(cache_shape, config.dtype),
    }
    token_sds = jax.ShapeDtypeStruct((B, 1), jnp.int32)
    pos_sds = jax.ShapeDtypeStruct((), jnp.int32)
    decode = jax.jit(
        lambda p, t, pos, c: T.decode_step(p, t, pos, c, config)
    ).lower(params_sds, token_sds, pos_sds, cache_sds)

    return {
        "case": "decode",
        "mesh": axes,
        "batch": [B, L],
        "prefill_lowered": bool(prefill.as_text()[:1]),
        "decode_lowered": bool(decode.as_text()[:1]),
    }


def main() -> None:
    if len(jax.devices()) < N_DEVICES:
        print(
            f"need {N_DEVICES} devices "
            f"(run with XLA_FLAGS=--xla_force_host_platform_device_count={N_DEVICES}); "
            f"have {len(jax.devices())}",
            file=sys.stderr,
        )
        sys.exit(2)
    print(json.dumps(validate_train({"fsdp": 8, "tp": 8})))
    print(json.dumps(validate_decode({"dp": 2, "sp": 4, "tp": 8})))
    print(
        json.dumps(
            validate_train(
                {"fsdp": 2, "ep": 8, "tp": 4},
                config=T.TransformerConfig.mixtral_8x7b(),
                case="train_moe",
            )
        )
    )


if __name__ == "__main__":
    main()
