#!/usr/bin/env python
"""KV-cached decode throughput on the real TPU chip.

Two measurements:

1. ``decode``: tokens/sec of the full incremental decode loop
   (models/transformer.decode_step — one lax.scan-compiled program updating
   the cache in place) on a ~1B-param llama-shaped config sized for one
   v5e chip's HBM.
2. ``decode_attention``: the attention inner loop in isolation — the
   grouped-query einsum (reads the compact [B, KVH, S, D] cache once)
   against the jnp.repeat broadcast variant it replaced. Decode is
   KV-cache-bandwidth-bound, so the repeat variant's H/KVH× extra HBM
   traffic is the whole story.

Timing: the decode loop is naturally self-chaining (each step consumes the
previous cache/token), so one jit + one scalar readback measures N real
steps — the same RTT-proof structure as scripts/bench-flash-attention.py
(per-call readbacks measured ~70 ms through the tunnel; see BASELINE.md
timing note).

Usage:  python scripts/bench-decode.py   (needs a reachable TPU; exits 2 if none)
Prints one JSON line per case.
"""

from __future__ import annotations

import dataclasses
import json
import math
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
from jax import lax


def run_measurements(emit) -> None:
    """All decode measurements, run inside an already-initialized jax
    process — callable from scripts/tpu-oneshot.py so one tunnel client
    captures the whole battery (see that script's docstring)."""
    from bee_code_interpreter_tpu.models.transformer import (
        TransformerConfig,
        decode_step,
        forward,
        init_decode_cache,
        init_params,
    )

    # ~1.1B params (f32 masters ~4.4 GB + bf16 cache) — fits one v5e chip
    config = TransformerConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
        n_kv_heads=4, d_ff=5632, max_seq_len=2048,
    )
    B, L_prompt, ctx = 8, 128, 2048
    params = init_params(config, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (B, L_prompt), 0, 32000)

    # prefill once to seed the cache
    logits, (k_pre, v_pre) = forward(params, prompt, config, None, return_kv=True)
    c = config
    first = jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)

    def decode_chain(step_fn, n_steps):
        """The ONE chained-decode loop both the contiguous and paged
        measurements compile — structurally identical by construction, so
        their comparison prices only the cache indexing.
        ``step_fn(tok, pos, cache) -> (logits, cache)``."""

        @jax.jit
        def f(tok, cache):
            def body(carry, pos):
                tok, cache = carry
                lg, cache = step_fn(tok, pos, cache)
                nxt = jnp.argmax(lg[:, -1:, :], axis=-1).astype(jnp.int32)
                return (nxt, cache), None

            (tok, _), _ = lax.scan(
                body, (tok, cache),
                jnp.arange(L_prompt, L_prompt + n_steps, dtype=jnp.int32),
            )
            return tok.astype(jnp.float32).sum()

        return f

    def decode_n(cfg, n_steps):
        return decode_chain(
            lambda tok, pos, cache: decode_step(params, tok, pos, cache, cfg),
            n_steps,
        )

    def best_of(f, *args, reps=3):
        float(f(*args))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    from bee_code_interpreter_tpu.utils.benchclock import chain_diff

    N = 64
    per_step = {}
    for name in ("bf16", "int8"):
        cfg = dataclasses.replace(config, kv_cache_dtype=name)
        cache0 = init_decode_cache(cfg, B, ctx, k_pre, v_pre)
        t_n = best_of(decode_n(cfg, N), first, cache0)
        t_1 = best_of(decode_n(cfg, 1), first, cache0)
        per_step[name] = chain_diff(t_n, t_1, N)
    # decode is HBM-bound: each step streams params (bf16 at compute) + cache
    cache_bytes = {
        "bf16": 2 * c.n_layers * B * c.kv_heads * ctx * c.head_dim * 2,
        "int8": 2 * c.n_layers * B * c.kv_heads * ctx * (c.head_dim + 4),
    }
    emit("decode", {
        "config": {"d_model": c.d_model, "n_layers": c.n_layers,
                   "heads": f"{c.n_heads}/{c.kv_heads}", "batch": B,
                   "ctx": ctx, "params": n_params},
        "per_step_ms": round(per_step["bf16"] * 1e3, 3),
        "tokens_per_sec": round(B / per_step["bf16"], 1),
        "int8_cache_per_step_ms": round(per_step["int8"] * 1e3, 3),
        "int8_cache_tokens_per_sec": round(B / per_step["int8"], 1),
        "int8_speedup": round(per_step["bf16"] / per_step["int8"], 2),
        "approx_hbm_gbps": round(
            (2 * n_params + cache_bytes["bf16"]) / per_step["bf16"] / 1e9, 1
        ),
        "int8_approx_hbm_gbps": round(
            (2 * n_params + cache_bytes["int8"]) / per_step["int8"] / 1e9, 1
        ),
    })

    # --- paged cache: the serving layout's cost vs the contiguous cache ----
    # Same config, same step count; the delta prices the block-table
    # gather/scatter indirection (the capacity win — densely shared pages
    # across heterogeneous requests — is free only if this tax is small).
    from bee_code_interpreter_tpu.models.transformer import decode_step_paged
    from bee_code_interpreter_tpu.ops.paged_kv_cache import (
        alloc_paged_cache,
        seed_prefill,
    )

    import math as _math

    ps = 64
    P = ctx // ps
    paged0 = alloc_paged_cache(config, n_pages=1 + B * P, page_size=ps)
    bt = (1 + jnp.arange(B * P, dtype=jnp.int32)).reshape(B, P)
    n_prompt_pages = _math.ceil(L_prompt / ps)
    for b in range(B):
        # seed only the pages the prompt occupies (the rest are already
        # zero; scattering them again is pure setup traffic)
        paged0 = seed_prefill(
            paged0, bt[b, :n_prompt_pages], k_pre[:, b], v_pre[:, b]
        )

    def decode_paged_n(n_steps):
        return decode_chain(
            lambda tok, pos, cache: decode_step_paged(
                params, tok, jnp.full((B,), pos), cache, bt, config
            ),
            n_steps,
        )

    t_pn = best_of(decode_paged_n(N), first, paged0)
    t_p1 = best_of(decode_paged_n(1), first, paged0)
    per_step_paged = chain_diff(t_pn, t_p1, N)
    emit("paged_decode", {
        "page_size": ps, "pages_per_seq": P,
        "per_step_ms": round(per_step_paged * 1e3, 3),
        "tokens_per_sec": round(B / per_step_paged, 1),
        "overhead_vs_contiguous": round(
            per_step_paged / per_step["bf16"] - 1.0, 3
        ),
    })

    # --- paged-attention kernel: in-place page reads vs the gather -------
    # (ops/paged_attention.py — the gather materializes a contiguous cache
    # copy per step; the kernel's speedup measures that copy's cost)
    kernel_cfg = dataclasses.replace(config, paged_attention_kernel=True)

    def decode_paged_kernel_n(n_steps):
        return decode_chain(
            lambda tok, pos, cache: decode_step_paged(
                params, tok, jnp.full((B,), pos), cache, bt, kernel_cfg
            ),
            n_steps,
        )

    t_kn = best_of(decode_paged_kernel_n(N), first, paged0)
    t_k1 = best_of(decode_paged_kernel_n(1), first, paged0)
    per_step_kernel = chain_diff(t_kn, t_k1, N)
    emit("paged_attention_kernel", {
        "per_step_ms": round(per_step_kernel * 1e3, 3),
        "tokens_per_sec": round(B / per_step_kernel, 1),
        "speedup_vs_gather_path": round(
            per_step_paged / per_step_kernel, 2
        ),
    })

    # --- weight-only int8: decode streams half the parameter bytes ------
    # (x @ q)*s epilogue form — ops/weight_quant.py; the win is pure HBM
    # bandwidth, so the speedup is the honest measure of how much of the
    # decode step the parameter stream actually is.
    from bee_code_interpreter_tpu.ops.weight_quant import quantize_weights

    qparams = quantize_weights(params)
    results_q = {}
    for name in ("bf16", "int8"):
        cfg = dataclasses.replace(config, kv_cache_dtype=name)
        cache0 = init_decode_cache(cfg, B, ctx, k_pre, v_pre)

        def decode_q_n(n_steps, cfg=cfg):
            return decode_chain(
                lambda tok, pos, cache: decode_step(
                    qparams, tok, pos, cache, cfg
                ),
                n_steps,
            )

        t_qn = best_of(decode_q_n(N), first, cache0)
        t_q1 = best_of(decode_q_n(1), first, cache0)
        results_q[name] = chain_diff(t_qn, t_q1, N)
    emit("w8a16_decode", {
        "per_step_ms": round(results_q["bf16"] * 1e3, 3),
        "tokens_per_sec": round(B / results_q["bf16"], 1),
        "speedup_vs_fp_weights": round(
            per_step["bf16"] / results_q["bf16"], 2
        ),
        "with_int8_kv_per_step_ms": round(results_q["int8"] * 1e3, 3),
        "with_int8_kv_tokens_per_sec": round(B / results_q["int8"], 1),
        "with_int8_kv_speedup_vs_fp_bf16": round(
            per_step["bf16"] / results_q["int8"], 2
        ),
    })

    # --- multi-LoRA serving: heterogeneous adapters riding the same paged
    # program (models/serving.py). The delta is unmerged per row, so the
    # overhead prices two rank-r einsums per target per layer — the
    # S-LoRA-style claim that N adapters share one base-weight HBM stream
    # is only real if this tax is small.
    from bee_code_interpreter_tpu.models.lora import (
        init_lora,
        stack_lora_bank,
    )

    n_adapters, rank = 8, 16
    adapters = [
        {
            t: {
                "A": ab["A"],
                "B": jax.random.normal(
                    jax.random.PRNGKey(200 + i), ab["B"].shape, jnp.float32
                ) * 0.02,
            }
            for t, ab in init_lora(
                config, jax.random.PRNGKey(100 + i), rank=rank
            ).items()
        }
        for i in range(n_adapters)
    ]
    bank = stack_lora_bank(adapters)
    # all-adapter mix: every row under a DIFFERENT adapter (1..8; per-step
    # cost is index-independent, but the labeled claim is 8 adapters/batch
    # so all 8 must actually be in the batch)
    ad_idx = 1 + jnp.arange(B, dtype=jnp.int32) % n_adapters

    def decode_lora_n(n_steps):
        return decode_chain(
            lambda tok, pos, cache: decode_step_paged(
                params, tok, jnp.full((B,), pos), cache, bt, config,
                lora_bank=bank, adapter_idx=ad_idx,
            ),
            n_steps,
        )

    t_ln = best_of(decode_lora_n(N), first, paged0)
    t_l1 = best_of(decode_lora_n(1), first, paged0)
    per_step_lora = chain_diff(t_ln, t_l1, N)
    emit("multilora_decode", {
        "n_adapters": n_adapters, "rank": rank,
        "targets": sorted(bank),
        "per_step_ms": round(per_step_lora * 1e3, 3),
        "tokens_per_sec": round(B / per_step_lora, 1),
        "overhead_vs_paged": round(
            per_step_lora / per_step_paged - 1.0, 3
        ),
    })

    # --- speculative decoding: tokens/sec with a small draft ---------------
    from bee_code_interpreter_tpu.models.speculative import speculative_generate

    draft_config = dataclasses.replace(
        config, n_layers=2, d_ff=2048, kv_cache_dtype="bf16"
    )
    draft_params = init_params(draft_config, jax.random.PRNGKey(9))
    spec_cfg = dataclasses.replace(config, kv_cache_dtype="bf16")
    n_spec, n_spec_small = 48, 8

    def run_spec_n(n):
        @jax.jit
        def f(prompt):
            return speculative_generate(
                params, spec_cfg, draft_params, draft_config, prompt,
                max_new_tokens=n, gamma=4,
            ).astype(jnp.float32).sum()

        return f

    # chain-diff between two lengths cancels the prefills + dispatch that
    # run_spec re-executes per call — the plain baseline below is the
    # prefill-free marginal per_step, so the comparison must be marginal too
    t_big = best_of(run_spec_n(n_spec), prompt)
    t_small = best_of(run_spec_n(n_spec_small), prompt)
    per_token_spec = chain_diff(t_big, t_small, n_spec - n_spec_small + 1)
    spec_toks_sec = B / per_token_spec
    emit("speculative_decode", {
        "draft": {"n_layers": draft_config.n_layers, "d_ff": draft_config.d_ff},
        "gamma": 4,
        "tokens_per_sec": round(spec_toks_sec, 1),
        "plain_tokens_per_sec": round(B / per_step["bf16"], 1),
        "speedup_vs_plain": round(
            spec_toks_sec / (B / per_step["bf16"]), 2
        ),
        "note": "random weights: draft-acceptance is adversarially low; a "
                "distilled draft on a trained target accepts far more",
    })

    # --- attention-only: grouped einsum vs repeat broadcast ---------------
    kvh, nh, dh, S = 8, 32, 128, 8192
    rep = nh // kvh
    kc = jax.random.normal(jax.random.PRNGKey(2), (B, kvh, S, dh), jnp.bfloat16)
    vc = jax.random.normal(jax.random.PRNGKey(3), (B, kvh, S, dh), jnp.bfloat16)
    q0 = jax.random.normal(jax.random.PRNGKey(4), (B, nh, dh), jnp.bfloat16)

    def grouped(q, k, v):
        qg = q.reshape(B, kvh, rep, dh).astype(jnp.float32)
        s = jnp.einsum("bgrd,bgsd->bgrs", qg, k.astype(jnp.float32)) / math.sqrt(dh)
        w = jax.nn.softmax(s, axis=-1).astype(k.dtype)
        return jnp.einsum("bgrs,bgsd->bgrd", w, v).reshape(B, nh, dh)

    def repeated(q, k, v):
        kf = jnp.repeat(k, rep, axis=1)
        vf = jnp.repeat(v, rep, axis=1)
        s = jnp.einsum(
            "bhd,bhsd->bhs", q.astype(jnp.float32), kf.astype(jnp.float32)
        ) / math.sqrt(dh)
        w = jax.nn.softmax(s, axis=-1).astype(k.dtype)
        return jnp.einsum("bhs,bhsd->bhd", w, vf)

    def chain(attn, n):
        @jax.jit
        def f(q, k, v):
            def body(c, _):
                return attn(c, k, v).astype(q.dtype), None

            c, _ = lax.scan(body, q, None, length=n)
            return c.astype(jnp.float32).sum()

        return f

    M = 32
    results = {}
    for name, fn in (("grouped", grouped), ("repeat", repeated)):
        t_m = best_of(chain(fn, M), q0, kc, vc)
        t_1 = best_of(chain(fn, 1), q0, kc, vc)
        results[name] = chain_diff(t_m, t_1, M)
    cache_bytes = 2 * kvh * S * dh * B * 2  # k+v, bf16
    emit("decode_attention", {
        "shape": {"batch": B, "heads": f"{nh}/{kvh}", "cache_len": S, "head_dim": dh},
        "grouped_us": round(results["grouped"] * 1e6, 1),
        "repeat_us": round(results["repeat"] * 1e6, 1),
        "speedup": round(results["repeat"] / results["grouped"], 2),
        "grouped_cache_gbps": round(cache_bytes / results["grouped"] / 1e9, 1),
    })


def main() -> None:
    import functools
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    probe = bench.probe_tpu()
    if not probe.get("ok") or probe.get("platform") != "tpu":
        print(f"no TPU: {probe}", file=sys.stderr)
        sys.exit(2)

    from bee_code_interpreter_tpu.utils import evidence

    run_measurements(
        functools.partial(evidence.emit, script="scripts/bench-decode.py")
    )


if __name__ == "__main__":
    main()
