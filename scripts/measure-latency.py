#!/usr/bin/env python
"""Measure /v1/execute latency percentiles (BASELINE.md north-star #3).

Drives the trivial health-check payload (``print(21 * 2)``) through two
execution backends and reports p50/p95/p99 PER STAGE (spawn/upload/execute/
download on the warm path; restore/execute/snapshot on the cold path) from
the tracing subsystem's per-request stage spans (docs/observability.md) —
a latency regression is attributed to the stage that moved, not guessed at
from a single end-to-end number.

- **warm**: NativeProcessCodeExecutor — warm pool of C++ sandbox servers, the
  TPU-native analogue of the reference's warm pod queue
  (kubernetes_code_executor.py:151-264). This is what a client observes when
  the pool keeps up.
- **cold**: LocalCodeExecutor — a fresh interpreter spawned per request; the
  pool-empty worst case (analogous to the reference's cold pod spawn, minus
  the k8s scheduling delay which depends on the cluster).

Usage: python scripts/measure-latency.py [N]    (default 30 requests each)
"""

from __future__ import annotations

import asyncio
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

PAYLOAD = "print(21 * 2)"

# Stage display order; stages a backend never produced are omitted.
STAGE_ORDER = (
    "spawn", "restore", "upload", "execute", "snapshot", "download",
)


def pct(samples: list[float], q: float) -> float:
    return statistics.quantiles(samples, n=100)[int(q) - 1]


def report_stages(name: str, stages: list[dict[str, float]],
                  totals_ms: list[float]) -> None:
    """p50/p95/p99 per stage (milliseconds). A request that skipped a stage
    (warm pop → no spawn; no files → no upload/download) contributes 0 to
    that stage, so the percentiles describe what clients actually pay."""
    seen = [s for s in STAGE_ORDER if any(s in d for d in stages)]
    print(f"{name}: n={len(totals_ms)}  (stage ms, then total)")
    for stage in [*seen, "total"]:
        vals = (
            totals_ms if stage == "total"
            else [float(d.get(stage, 0.0)) for d in stages]
        )
        print(
            f"  {stage:>9}: p50={pct(vals, 50):8.1f}  "
            f"p95={pct(vals, 95):8.1f}  p99={pct(vals, 99):8.1f}"
        )


async def bench_warm(n: int) -> tuple[list[dict], list[float]]:
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.observability import Tracer
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="lat-warm-"))
    config = Config(
        file_storage_path=str(tmp / "objects"),
        local_workspace_root=str(tmp / "ws"),
        executor_pod_queue_target_length=4,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=Storage(tmp / "objects"),
        config=config,
        binary=REPO / "executor" / "build" / "executor-server",
    )
    tracer = Tracer()
    try:
        await executor.fill_sandbox_queue()
        stages: list[dict] = []
        totals: list[float] = []
        phases: list[dict] = []
        for i in range(n):
            if i:
                # measure request latency, not saturated throughput: give the
                # refill pipeline room so pops hit preload-complete sandboxes
                await asyncio.sleep(0.35)
            t0 = time.perf_counter()
            with tracer.trace("measure-latency") as t:
                r = await executor.execute(PAYLOAD)
            assert r.stdout == "42\n", r.stderr
            totals.append((time.perf_counter() - t0) * 1000)
            stages.append(t.stage_ms())
            phases.append(dict(executor.last_execute_phases))
        # the native backend's own internal phase probe, complementary to
        # the trace stages (it sees inside the HTTP call: sandbox vs
        # control-plane overhead)
        keys = ("acquire_ms", "upload_ms", "post_execute_ms", "sandbox_ms",
                "overhead_ms", "download_ms")
        for q in (50, 90):
            row = {
                k: pct([float(p.get(k, 0.0)) for p in phases], q)
                for k in keys
            }
            print(
                f"warm phases p{q}: "
                + "  ".join(f"{k}={v:.1f}" for k, v in row.items())
            )
        return stages, totals
    finally:
        executor.shutdown()


async def bench_cold(n: int) -> tuple[list[dict], list[float]]:
    from bee_code_interpreter_tpu.observability import Tracer
    from bee_code_interpreter_tpu.services.local_code_executor import (
        LocalCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="lat-cold-"))
    executor = LocalCodeExecutor(
        storage=Storage(tmp / "objects"),
        workspace_root=tmp / "ws",
        disable_dep_install=True,
    )
    tracer = Tracer()
    stages: list[dict] = []
    totals: list[float] = []
    for _ in range(n):
        t0 = time.perf_counter()
        with tracer.trace("measure-latency") as t:
            r = await executor.execute(PAYLOAD)
        assert r.stdout == "42\n", r.stderr
        totals.append((time.perf_counter() - t0) * 1000)
        stages.append(t.stage_ms())
    return stages, totals


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    import subprocess

    subprocess.run(["make", "-C", str(REPO / "executor"), "-s"], check=True)
    for name, fn in (("warm", bench_warm), ("cold", bench_cold)):
        stages, totals = asyncio.run(fn(n))
        report_stages(name, stages, totals)


if __name__ == "__main__":
    main()
