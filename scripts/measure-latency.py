#!/usr/bin/env python
"""Measure /v1/execute latency percentiles (BASELINE.md north-star #3).

Drives the trivial health-check payload (``print(21 * 2)``) through two
execution backends and reports p50/p90:

- **warm**: NativeProcessCodeExecutor — warm pool of C++ sandbox servers, the
  TPU-native analogue of the reference's warm pod queue
  (kubernetes_code_executor.py:151-264). This is what a client observes when
  the pool keeps up.
- **cold**: LocalCodeExecutor — a fresh interpreter spawned per request; the
  pool-empty worst case (analogous to the reference's cold pod spawn, minus
  the k8s scheduling delay which depends on the cluster).

Usage: python scripts/measure-latency.py [N]    (default 30 requests each)
"""

from __future__ import annotations

import asyncio
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

PAYLOAD = "print(21 * 2)"


def pct(samples: list[float], q: float) -> float:
    return statistics.quantiles(samples, n=100)[int(q) - 1]


async def bench_warm(n: int) -> list[float]:
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="lat-warm-"))
    config = Config(
        file_storage_path=str(tmp / "objects"),
        local_workspace_root=str(tmp / "ws"),
        executor_pod_queue_target_length=4,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=Storage(tmp / "objects"),
        config=config,
        binary=REPO / "executor" / "build" / "executor-server",
    )
    try:
        await executor.fill_sandbox_queue()
        samples = []
        phases: list[dict] = []
        for i in range(n):
            if i:
                # measure request latency, not saturated throughput: give the
                # refill pipeline room so pops hit preload-complete sandboxes
                await asyncio.sleep(0.35)
            t0 = time.perf_counter()
            r = await executor.execute(PAYLOAD)
            assert r.stdout == "42\n", r.stderr
            samples.append(time.perf_counter() - t0)
            phases.append(dict(executor.last_execute_phases))
        keys = ("acquire_ms", "upload_ms", "post_execute_ms", "sandbox_ms",
                "overhead_ms", "download_ms")
        for q in (50, 90):
            row = {
                k: pct([float(p.get(k, 0.0)) for p in phases], q)
                for k in keys
            }
            print(
                f"warm phases p{q}: "
                + "  ".join(f"{k}={v:.1f}" for k, v in row.items())
            )
        return samples
    finally:
        executor.shutdown()


async def bench_cold(n: int) -> list[float]:
    from bee_code_interpreter_tpu.services.local_code_executor import (
        LocalCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = Path(tempfile.mkdtemp(prefix="lat-cold-"))
    executor = LocalCodeExecutor(
        storage=Storage(tmp / "objects"),
        workspace_root=tmp / "ws",
        disable_dep_install=True,
    )
    samples = []
    for _ in range(n):
        t0 = time.perf_counter()
        r = await executor.execute(PAYLOAD)
        assert r.stdout == "42\n", r.stderr
        samples.append(time.perf_counter() - t0)
    return samples


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    import subprocess

    subprocess.run(["make", "-C", str(REPO / "executor"), "-s"], check=True)
    for name, fn in (("warm", bench_warm), ("cold", bench_cold)):
        s = asyncio.run(fn(n))
        print(
            f"{name}: n={n} p50={pct(s, 50) * 1000:.1f}ms "
            f"p90={pct(s, 90) * 1000:.1f}ms min={min(s) * 1000:.1f}ms"
        )


if __name__ == "__main__":
    main()
