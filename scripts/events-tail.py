#!/usr/bin/env python3
"""Live tail of the flight recorder's wide events (docs/observability.md).

Follows ``GET /v1/events?follow=1`` over SSE and renders each wide event as
a one-line table row (or raw JSON with ``--json``) — `tail -f` for the
service's request journal, with the same filters the API supports:

    python scripts/events-tail.py [--url http://localhost:50081]
        [--outcome error] [--session sess-...] [--tenant alpha]
        [--kind serving] [--min-duration-ms 500] [--backlog 20]
        [--json] [--once]

``--once`` skips the follow and prints the current snapshot instead.

Point ``--url`` at a FLEET ROUTER edge (docs/observability.md "Fleet
observability") and the same commands work fleet-wide: ``--once`` renders
the federated merge (router + every live replica, each event's ``source``
in the first column), while the follow mode tails the router's own
``kind=routing`` / ``kind=lease_migrate`` decision journal live — each row
carrying the trace_id that joins it to the distributed trace.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import httpx


def fmt_ts(ts: float | None) -> str:
    if ts is None:
        return "-"
    return time.strftime("%H:%M:%S", time.localtime(ts))


def render(event: dict) -> str:
    duration = event.get("duration_ms")
    dur = f"{duration:8.1f}ms" if duration is not None else "         -"
    stream = event.get("stream") or {}
    extras = []
    if event.get("tenant"):
        extras.append(f"tenant={event['tenant']}")
    if event.get("session"):
        extras.append(f"session={event['session']}")
    if stream.get("chunks"):
        extras.append(f"chunks={stream['chunks']:g}")
    if stream.get("ttfb_ms") is not None:
        extras.append(f"ttfb={stream['ttfb_ms']:.0f}ms")
    if event.get("replays"):
        extras.append(f"replays={event['replays']}")
    if event.get("hedge"):
        extras.append(f"hedge={event['hedge']}")
    if event.get("kind") == "loop_stall":
        extras.append(f"lag={event.get('lag_s', 0) * 1000:.0f}ms")
    if event.get("kind") == "routing":
        # One router decision (docs/fleet.md): chosen replica, ring
        # verdict, and how many cross-replica retries the client never saw.
        extras.append(f"replica={event.get('replica') or '-'}")
        if event.get("affinity"):
            extras.append(f"affinity={event['affinity']}")
        if event.get("retries"):
            extras.append(f"retries={event['retries']}")
    if event.get("kind") == "lease_migrate":
        extras.append(
            f"{event.get('from', '?')}->{event.get('to') or '?'}"
        )
    if event.get("kind") == "compile":
        # One XLA compilation (docs/observability.md "Accelerator
        # observability"): which jitted function, what triggered it
        # (first_call vs retrace), and the abstract input signature that
        # forced the new executable.
        extras.append(
            f"{event.get('function', '?')}[{event.get('trigger', '?')}]"
        )
        if event.get("signature"):
            extras.append(f"sig={event['signature']}")
        if event.get("mesh"):
            extras.append(f"mesh={event['mesh']}")
    if event.get("kind") == "autoscale":
        # One scaling decision (docs/autoscaling.md): direction, size
        # delta, reason, and whether act mode actually moved the pool.
        extras.append(
            f"{event.get('direction', '?')} {event.get('from', '?')}"
            f"->{event.get('to', '?')} reason={event.get('reason', '?')}"
        )
        extras.append(
            f"mode={event.get('mode', '?')}"
            + ("" if event.get("applied") else " (not applied)")
        )
    serving = event.get("serving") or {}
    if serving:
        extras.append(
            f"tokens={serving.get('prompt_tokens', 0)}"
            f"+{serving.get('output_tokens', 0)}"
        )
        if serving.get("ttft_ms") is not None:
            extras.append(f"ttft={serving['ttft_ms']:.1f}ms")
        if serving.get("requeues"):
            extras.append(f"requeues={serving['requeues']}")
    # Federated rows (a router edge merging N replicas) carry their origin;
    # single-replica rows don't — omit the column rather than pad it.
    source = f" {event['source']:<8}" if event.get("source") else ""
    return (
        f"{fmt_ts(event.get('ts'))}{source} {event.get('kind', '-'):<10} "
        f"{(event.get('name') or '-'):<32} {(event.get('outcome') or '-'):<12} "
        f"{dur}  trace={event.get('trace_id') or '-':<32} "
        + " ".join(extras)
    )


def emit(event: dict, as_json: bool) -> None:
    print(json.dumps(event) if as_json else render(event), flush=True)


def tail(client: httpx.Client, base: str, params: dict, as_json: bool) -> int:
    # SSE: "event: wide_event" lines name the event, "data: {...}" carries
    # it, a blank line ends each record; ": keep-alive" comments are noise.
    with client.stream(
        "GET", f"{base}/v1/events", params={**params, "follow": "1"},
        timeout=httpx.Timeout(10.0, read=None),
    ) as response:
        response.raise_for_status()
        data_lines: list[str] = []
        for line in response.iter_lines():
            if line.startswith("data:"):
                data_lines.append(line[len("data:"):].strip())
            elif not line.strip():
                if data_lines:
                    emit(json.loads("\n".join(data_lines)), as_json)
                    data_lines = []
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Tail GET /v1/events?follow=1 (wide-event journal)."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    parser.add_argument("--outcome", help="filter by outcome (e.g. error)")
    parser.add_argument("--session", help="filter by session id")
    parser.add_argument("--tenant", help="filter by tenant label")
    parser.add_argument(
        "--kind",
        help="filter by kind (request/session/serving/compile/loop_stall/"
        "autoscale)",
    )
    parser.add_argument("--min-duration-ms", type=float, default=None)
    parser.add_argument(
        "--backlog", type=int, default=10,
        help="replay the last N matching events before following (default 10)",
    )
    parser.add_argument(
        "--json", action="store_true", help="print raw JSON instead of the table"
    )
    parser.add_argument(
        "--once", action="store_true",
        help="print the current snapshot and exit (no follow)",
    )
    args = parser.parse_args()
    base = args.url.rstrip("/")
    params: dict = {}
    if args.outcome:
        params["outcome"] = args.outcome
    if args.session:
        params["session"] = args.session
    if args.tenant:
        params["tenant"] = args.tenant
    if args.kind:
        params["kind"] = args.kind
    if args.min_duration_ms is not None:
        params["min_duration_ms"] = args.min_duration_ms
    try:
        with httpx.Client() as client:
            if args.once:
                body = (
                    client.get(
                        f"{base}/v1/events",
                        params={**params, "limit": max(0, args.backlog)},
                        timeout=10.0,
                    )
                    .raise_for_status()
                    .json()
                )
                for event in reversed(body["events"]):  # oldest first
                    emit(event, args.json)
                return 0
            return tail(
                client, base, {**params, "backlog": max(0, args.backlog)},
                args.json,
            )
    except httpx.HTTPError as e:
        print(f"events-tail: cannot reach {base}: {e}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
