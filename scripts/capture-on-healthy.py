#!/usr/bin/env python
"""Watch the TPU tunnel and run the full hardware battery the moment it is
healthy — the capture-on-healthy process (VERDICT r3 next-round #1/#2).

The tunnel to the chip flips between healthy and wedged within sessions
(BASELINE.md rounds 1-3), so hardware evidence cannot be a point-in-time
measurement taken whenever a driver happens to run. This watcher probes on a
cadence (bounded, out-of-process — a wedged tunnel hangs the probe
subprocess, never the watcher) and, on the first healthy probe, runs every
hardware-touching script in sequence:

  1. bench.py (short patience — the headline dense-matmul GFLOPS + flash)
  2. scripts/validate-shardmap-pallas.py  (Mosaic-under-shard_map proof)
  3. scripts/bench-flash-attention.py     (kernel TFLOPS vs 2 XLA baselines)
  4. scripts/bench-decode.py              (decode tok/s, int8, speculative)
  5. scripts/bench-mfu.py                 (flagship MFU via the service path)

Each script appends its own measurements to TPU_EVIDENCE.jsonl (see
utils/evidence.py), so one healthy window yields a dated, git-attributed
ledger that bench.py embeds in every later artifact even if the tunnel is
wedged again by then. Scripts exiting 2 (chip vanished mid-battery) put the
watcher back into its probe loop.

Usage:
  python scripts/capture-on-healthy.py              # until battery completes
  python scripts/capture-on-healthy.py --forever    # keep re-capturing
  python scripts/capture-on-healthy.py --interval 120 --max-hours 10
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# (argv, per-script timeout seconds). Generous: one compile can take ~40 s
# through the tunnel and the decode/MFU scripts compile several programs.
BATTERY: list[tuple[list[str], float]] = [
    ([sys.executable, str(REPO / "bench.py")], 900.0),
    ([sys.executable, str(REPO / "scripts" / "validate-shardmap-pallas.py")], 600.0),
    ([sys.executable, str(REPO / "scripts" / "bench-flash-attention.py")], 1200.0),
    ([sys.executable, str(REPO / "scripts" / "bench-decode.py")], 1500.0),
    ([sys.executable, str(REPO / "scripts" / "bench-mfu.py")], 1500.0),
]


def load_probe():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench.probe_tpu


def log(msg: str) -> None:
    print(f"[capture {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def run_battery() -> bool:
    """Run every battery script; True iff all succeeded (exit 0)."""
    all_ok = True
    for argv, timeout_s in BATTERY:
        name = Path(argv[-1]).name
        if not Path(argv[-1]).exists():
            log(f"{name}: missing, skipped")
            continue
        log(f"running {name} (timeout {timeout_s:.0f}s)")
        env = dict(os.environ)
        if name == "bench.py":
            # The watcher IS the patience; bench itself should not re-wait.
            env["BCI_BENCH_TPU_PATIENCE_S"] = "90"
        t0 = time.time()
        try:
            out = subprocess.run(
                argv, capture_output=True, text=True,
                timeout=timeout_s, cwd=REPO, env=env,
            )
        except subprocess.TimeoutExpired:
            log(f"{name}: TIMED OUT after {timeout_s:.0f}s (tunnel wedged mid-run?)")
            all_ok = False
            continue
        dt = time.time() - t0
        for line in out.stdout.splitlines():
            log(f"{name}: {line}")
        if out.returncode == 2:
            log(f"{name}: chip unreachable (exit 2) after {dt:.0f}s — back to probing")
            return False
        if out.returncode != 0:
            log(f"{name}: FAILED exit {out.returncode} after {dt:.0f}s; "
                f"stderr tail: {out.stderr[-500:]}")
            all_ok = False
        else:
            log(f"{name}: ok in {dt:.0f}s")
    return all_ok


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=90.0,
                    help="seconds between probes while wedged")
    ap.add_argument("--max-hours", type=float, default=12.0)
    ap.add_argument("--forever", action="store_true",
                    help="keep re-capturing after a successful battery "
                         "(cooldown = 10x interval)")
    args = ap.parse_args()

    probe_tpu = load_probe()
    deadline = time.time() + args.max_hours * 3600
    captures = 0
    while time.time() < deadline:
        probe = probe_tpu()
        log(f"probe: {json.dumps(probe)}")
        if probe.get("ok") and probe.get("platform") == "tpu":
            log("tunnel HEALTHY — running battery")
            if run_battery():
                captures += 1
                log(f"battery complete (capture #{captures})")
                if not args.forever:
                    return
                time.sleep(args.interval * 10)
                continue
            log("battery incomplete — resuming probe loop")
        time.sleep(args.interval)
    log(f"max-hours reached; {captures} complete captures")
    sys.exit(0 if captures else 3)


if __name__ == "__main__":
    main()
