#!/usr/bin/env python
"""Patient TPU capture loop around the one-client battery.

Round-4 discovery (see scripts/tpu-oneshot.py): the tunnel serves at best
one jax client per healthy window, killed clients appear to hold it wedged,
and it recovered only after ~5.4 h of complete quiet. The round-3 design —
a 60-90 s probe cadence, each hung probe killed at 75 s, then five separate
measurement processes — is exactly wrong for that behavior: the probe storm
PREVENTS recovery and the throwaway probe client burns the window.

This loop therefore:

  1. Launches ``scripts/tpu-oneshot.py`` directly — its jax init IS the
     probe; on success the same process captures every measurement into
     TPU_EVIDENCE.jsonl. No separate probe client.
  2. Sleeps a LONG, escalating interval between attempts (default start
     10 min, x1.5 up to 45 min) so a recovering tunnel gets real quiet time.
  3. After a successful battery, runs the service-path follow-ups — bench.py
     (the /v1/execute headline) and scripts/bench-mfu.py (service-path MFU
     row) — which need fresh sandbox-subprocess clients and so only make
     sense once a window has proven healthy.

Usage:
  python scripts/capture-on-healthy.py                  # until one capture
  python scripts/capture-on-healthy.py --forever        # keep re-capturing
  python scripts/capture-on-healthy.py --interval 300 --max-hours 10
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

ONESHOT = REPO / "scripts" / "tpu-oneshot.py"
# Follow-ups spawn sandbox subprocesses (fresh tunnel clients); run only
# after the one-client battery proved the window healthy.
FOLLOWUPS: list[tuple[list[str], float]] = [
    ([sys.executable, str(REPO / "bench.py")], 900.0),
    ([sys.executable, str(REPO / "scripts" / "bench-mfu.py")], 1500.0),
]


def log(msg: str) -> None:
    print(f"[capture {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def run_oneshot(timeout_s: float) -> int:
    """One battery attempt. The oneshot self-exits on a hung init (3) or a
    mid-run stall (4); the outer timeout is a backstop only."""
    log(f"launching one-client battery (backstop timeout {timeout_s:.0f}s)")
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, str(ONESHOT)], capture_output=True, text=True,
            timeout=timeout_s, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        log("oneshot: backstop timeout — watchdog failed? treating as stall")
        return 4
    dt = time.time() - t0
    for line in (out.stdout + out.stderr).splitlines():
        log(f"oneshot: {line}")
    log(f"oneshot: exit {out.returncode} after {dt:.0f}s")
    return out.returncode


def run_followups() -> None:
    for argv, timeout_s in FOLLOWUPS:
        name = Path(argv[-1]).name
        log(f"running follow-up {name} (timeout {timeout_s:.0f}s)")
        env = dict(os.environ)
        if name == "bench.py":
            # The loop is the patience; bench itself should not re-wait long.
            env["BCI_BENCH_TPU_PATIENCE_S"] = "180"
        t0 = time.time()
        try:
            out = subprocess.run(
                argv, capture_output=True, text=True,
                timeout=timeout_s, cwd=REPO, env=env,
            )
        except subprocess.TimeoutExpired:
            log(f"{name}: TIMED OUT after {timeout_s:.0f}s (window closed?)")
            continue
        for line in out.stdout.splitlines():
            log(f"{name}: {line}")
        if out.returncode != 0:
            log(f"{name}: stderr tail: {out.stderr[-500:]}")
        log(f"{name}: exit {out.returncode} after {time.time() - t0:.0f}s")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0,
                    help="starting seconds between battery attempts")
    ap.add_argument("--max-interval", type=float, default=2700.0)
    ap.add_argument("--max-hours", type=float, default=12.0)
    ap.add_argument("--forever", action="store_true",
                    help="keep re-capturing after a successful battery")
    ap.add_argument("--skip-followups", action="store_true",
                    help="one-client battery only (no sandbox-path runs)")
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    interval = args.interval
    captures = 0
    while time.time() < deadline:
        # Backstop > the oneshot's realistic worst case (~45 min of real
        # measurements + one 1800s stall before its own watchdog fires):
        # the backstop must never SIGKILL a battery the child's watchdog
        # considers healthy — a hard-killed client is the tunnel-wedging
        # pattern this whole design exists to avoid.
        rc = run_oneshot(timeout_s=7200.0)
        if rc in (0, 5, 6):
            # Even a partially/fully failed battery proved the tunnel
            # serves clients right now — the follow-ups may still land,
            # and a partial battery (6) is worth retrying for the rest.
            if rc == 0:
                captures += 1
                log(f"battery complete (capture #{captures})")
            else:
                log("battery partial/failed — will keep trying")
            if not args.skip_followups:
                run_followups()
            if rc == 0 and not args.forever:
                return
            interval = args.interval  # healthy-ish: reset the backoff
        elif rc == 2:
            log("backend is not TPU here; nothing to capture")
            sys.exit(2)
        else:  # 3 = init hung, 4 = stalled mid-run: give the tunnel quiet
            log(f"tunnel wedged (exit {rc}); quiet for {interval:.0f}s")
        remaining = deadline - time.time()
        if remaining <= 0:
            break
        time.sleep(min(interval, max(remaining, 1.0)))
        interval = min(interval * 1.5, args.max_interval)
    log(f"max-hours reached; {captures} complete captures")
    sys.exit(0 if captures else 3)


if __name__ == "__main__":
    main()
