#!/usr/bin/env python
"""ONE-CLIENT TPU capture battery: every hardware measurement in one process.

Why this exists (round-4 discovery): the TPU tunnel serves — at best — one
jax client per healthy window. Observed this session: a bounded probe
succeeded in 2.8 s after hours of idleness; a second client started 9 s
later (after the first exited CLEANLY) hung past 75 s; and every client
since hung too. Under that behavior the previous architecture — probe in a
subprocess, then measure in a fresh process, across five separate scripts —
burns the whole healthy window on the throwaway probe. Worse, a probe loop
on a 60 s cadence (each hung probe killed at 75 s) appears to HOLD the
tunnel wedged: the round-4 first session logged 126 consecutive hung probes
over ~6 h, and the tunnel recovered only after ~5.4 h of complete quiet.

So this script is both the probe AND the battery:

  - Its own ``jax.devices()`` is the probe. If init doesn't complete within
    ``BCI_ONESHOT_INIT_TIMEOUT_S`` (default 150 s), a watchdog thread exits
    3 — the caller (scripts/capture-on-healthy.py) sleeps a LONG interval
    and retries. No separate probe client ever touches the tunnel.
  - On success it runs EVERY measurement in this one process, appending each
    to TPU_EVIDENCE.jsonl the moment it lands (utils/evidence.py), most
    valuable first, so a tunnel that wedges mid-battery still leaves a
    partial ledger:
      1. dense-matmul chain (the north-star payload math, in-process)
      2. flash-attention numerics + throughput (bench-flash-attention)
      3. Pallas-under-shard_map Mosaic validation (validate-shardmap-pallas)
      4. KV-decode battery: bf16/int8, paged, speculative (bench-decode)
      5. flagship train MFU + decode (bench-mfu payload, exec'd in-process)
  - A deadman watchdog exits 4 if any single case stalls past
    ``BCI_ONESHOT_STALL_S`` (default 1800 s — the decode case alone jit-
    compiles ~20 programs at ~20-40 s each through the tunnel) — a
    mid-run wedge must not hold
    a zombie client open all night (that blocks the tunnel's own recovery).

Service-path variants (bench.py's /v1/execute headline, bench-mfu's service
row) need fresh sandbox processes = more clients; the caller runs those
AFTER this battery exits, when the window has already proven healthy.

Exit codes: 0 = battery complete; 2 = backend is not TPU; 3 = init hung
(wedged tunnel); 4 = stalled mid-battery; 5 = every case failed; 6 = some
cases failed (the caller should keep trying for the rest).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

INIT_TIMEOUT_S = float(os.environ.get("BCI_ONESHOT_INIT_TIMEOUT_S", "150"))
STALL_TIMEOUT_S = float(os.environ.get("BCI_ONESHOT_STALL_S", "1800"))

_progress = {"mark": time.time(), "stage": "init"}


def _bump(stage: str) -> None:
    _progress["mark"] = time.time()
    _progress["stage"] = stage
    print(f"[oneshot {time.strftime('%H:%M:%S')}] {stage}",
          file=sys.stderr, flush=True)


def _watchdog() -> None:
    while True:
        time.sleep(5)
        stalled = time.time() - _progress["mark"]
        limit = INIT_TIMEOUT_S if _progress["stage"] == "init" else STALL_TIMEOUT_S
        if stalled > limit:
            code = 3 if _progress["stage"] == "init" else 4
            print(
                f"[oneshot] watchdog: stage '{_progress['stage']}' stalled "
                f"{stalled:.0f}s (limit {limit:.0f}s) — exit {code}",
                file=sys.stderr, flush=True,
            )
            os._exit(code)


def _load_script(name: str, *, root: bool = False):
    """Import a dashed-name sibling script — or, with ``root=True``, a
    repo-root module like bench.py — as a module."""
    path = (REPO if root else REPO / "scripts") / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name.replace("-", "_"), path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _dense_matmul(emit) -> None:
    """The north-star payload math (bench.py's TPU_PAYLOAD: bf16 matmul
    chain), measured in-process. bench.py's own run drives the identical
    chain through /v1/execute; this entry exists so the number cannot be
    lost to a window too short for a sandbox subprocess. Shape constants
    come off bench.py itself so the two can never silently diverge."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    bench = _load_script("bench", root=True)
    n, iters = bench.N, bench.ITERS
    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype=jnp.bfloat16)

    @jax.jit
    def chain(a):
        a = a * jnp.bfloat16(1 / 128)

        def body(i, x):
            return a @ x

        return lax.fori_loop(0, iters, body, a).sum()

    float(chain(a))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        float(chain(a))
        best = min(best, time.time() - t0)
    emit("dense_matmul_inprocess", {
        "gflops": round(2 * n**3 * iters / best / 1e9, 1),
        "payload": f"bf16 {n}^3 jit chain, in-process one-client battery",
    })


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()

    t0 = time.time()
    import jax  # the probe IS the init

    devices = jax.devices()
    init_s = round(time.time() - t0, 1)
    if devices[0].platform != "tpu":
        print(f"backend is {devices[0].platform}, not tpu", file=sys.stderr)
        sys.exit(2)
    _bump(f"connected ({init_s}s, {devices[0]})")

    import functools

    from bee_code_interpreter_tpu.utils import evidence

    evidence.record(
        "tunnel_health",
        {"init_seconds": init_s, "device": str(devices[0]),
         "note": "healthy window: jax client initialized"},
        script="scripts/tpu-oneshot.py",
    )

    def emit_for(script: str):
        return functools.partial(evidence.emit, script=script)

    flash = _load_script("bench-flash-attention")
    shardmap = _load_script("validate-shardmap-pallas")
    decode = _load_script("bench-decode")
    mfu = _load_script("bench-mfu")

    def run_shardmap():
        # run_measurements returns False on a numerics mismatch (it prints
        # its JSON instead of raising) — surface that as a case failure, not
        # a silent pass
        if shardmap.run_measurements(
            emit_for("scripts/validate-shardmap-pallas.py")
        ) is False:
            raise RuntimeError("shard_map validation numerics mismatch")

    cases = [
        ("dense_matmul", lambda: _dense_matmul(emit_for("scripts/tpu-oneshot.py"))),
        ("flash", lambda: flash.run_measurements(
            emit_for("scripts/bench-flash-attention.py"))),
        ("shardmap_pallas", run_shardmap),
        ("decode", lambda: decode.run_measurements(
            emit_for("scripts/bench-decode.py"))),
        ("mfu_inprocess", lambda: mfu.run_inprocess(
            emit_for("scripts/bench-mfu.py"))),
    ]
    failures: list[str] = []
    for name, run in cases:
        _bump(f"case {name}")
        try:
            run()
            _bump(f"case {name} done")
        except Exception as e:  # one case must not cost the rest the window
            failures.append(name)
            print(f"[oneshot] case {name} FAILED: {e!r}", file=sys.stderr,
                  flush=True)
    _bump("battery complete")
    print(json.dumps({
        "oneshot": "complete",
        "init_seconds": init_s,
        "cases_ok": [n for n, _ in cases if n not in failures],
        "cases_failed": failures,
    }), flush=True)
    if len(failures) == len(cases):
        sys.exit(5)
    if failures:
        sys.exit(6)  # partial: the caller should keep trying for the rest


if __name__ == "__main__":
    main()
