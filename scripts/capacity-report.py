#!/usr/bin/env python3
"""Terminal rendering of the measured capacity artifact (docs/capacity.md).

Per configuration: the max-sustained-rps-at-SLO headline, the p99-vs-load
curve the knee search walked (every probe, with the criteria that failed
named), the flash-crowd account (sheds by tenant, warm-pool hit ratio,
the forecaster's replica recommendation while the crowd burned), and the
router's per-stage p50 tax when the configuration had one.

    python scripts/capacity-report.py [CAPACITY_r01.json]

Exit codes: 0 rendered, 1 unreadable artifact, 2 a configuration whose
knee is 0.0 (nothing sustained — the probe floor itself failed).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

DEFAULT_ARTIFACT = Path(__file__).resolve().parent.parent / "CAPACITY_r01.json"


def _fmt(value, suffix: str = "") -> str:
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.1f}{suffix}"
    return f"{value}{suffix}"


def render_config(name: str, config: dict) -> list[str]:
    shape = f"{config.get('replicas', '?')} replica(s)"
    if config.get("router"):
        shape += " behind the fleet router"
    lines = [
        f"config {name} — {shape}",
        f"  max sustained: {config.get('max_sustained_rps', 0.0):g} rps at SLO",
        f"  {'OFFERED':>8} {'ACHIEVED':>9} {'P50':>8} {'P99':>9} "
        f"{'SHEDS':>6} {'ERRS':>5}  VERDICT",
    ]
    lines.append("  " + "-" * (len(lines[-1]) - 2))
    for point in sorted(
        config.get("curve", []), key=lambda p: p.get("offered_rps", 0.0)
    ):
        verdict = (
            "sustained"
            if point.get("sustained")
            else "; ".join(point.get("reasons") or ["unsustained"])
        )
        lines.append(
            f"  {_fmt(point.get('offered_rps')):>8} "
            f"{_fmt(point.get('achieved_rps')):>9} "
            f"{_fmt(point.get('p50_ms'), 'ms'):>8} "
            f"{_fmt(point.get('p99_ms'), 'ms'):>9} "
            f"{_fmt(point.get('sheds')):>6} "
            f"{_fmt(point.get('errors')):>5}  {verdict}"
        )
    crowd = config.get("flash_crowd")
    if crowd:
        lines.append(
            f"  flash crowd: offered {crowd.get('offered')} "
            f"(peak {_fmt(crowd.get('offered_rps'))} rps mean), "
            f"completed {crowd.get('completed')}, "
            f"sheds {crowd.get('sheds')}, errors {crowd.get('errors')}"
        )
        ledger = crowd.get("shed_ledger") or {}
        if ledger:
            by_tenant = ", ".join(
                f"{tenant}={count}" for tenant, count in sorted(ledger.items())
            )
            lines.append(f"    shed ledger: {by_tenant}")
        warm = crowd.get("warm_pop_ratio")
        if warm is not None:
            lines.append(f"    warm_pop_ratio under crowd: {warm:.2f}")
        rec = crowd.get("recommendation") or {}
        if rec:
            lines.append(
                f"    forecaster recommendation: "
                f"{rec.get('target_replicas')} replicas "
                f"({rec.get('reason')}; have {rec.get('current_replicas')})"
            )
        lines.append(
            f"    fast-burn page fired: {bool(crowd.get('fast_burn'))}"
        )
    stages = config.get("router_stage_p50_ms")
    if stages:
        tax = ", ".join(f"{k}={v:g}ms" for k, v in sorted(stages.items()))
        lines.append(f"  router stage p50: {tax}")
    return lines


def render(artifact: dict) -> str:
    slo = artifact.get("slo") or {}
    host = artifact.get("host") or {}
    lines = [
        f"capacity artifact {artifact.get('version', '?')} — "
        f"{artifact.get('generated_at', 'undated')} on "
        f"{host.get('platform', '?')}/{host.get('cpus', '?')}cpu "
        f"({artifact.get('wall_s', '?')}s wall)",
        f"SLO: p99 <= {slo.get('p99_ms', '?'):g}ms, "
        f"errors <= {slo.get('error_budget', 0):.1%}, "
        f"sheds <= {slo.get('shed_budget', 0):.1%}",
        "",
    ]
    for name in sorted(artifact.get("configs") or {}):
        lines.extend(render_config(name, artifact["configs"][name]))
        lines.append("")
    return "\n".join(lines).rstrip()


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render CAPACITY_r01.json as a terminal table."
    )
    parser.add_argument(
        "artifact", nargs="?", default=str(DEFAULT_ARTIFACT),
        help="path to the capacity artifact (default: repo root)",
    )
    args = parser.parse_args()
    try:
        artifact = json.loads(Path(args.artifact).read_text())
    except (OSError, ValueError) as e:
        print(f"capacity-report: cannot read {args.artifact}: {e}",
              file=sys.stderr)
        return 1
    print(render(artifact))
    configs = artifact.get("configs") or {}
    if any(
        not c.get("max_sustained_rps") for c in configs.values()
    ) or not configs:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
