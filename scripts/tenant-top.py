#!/usr/bin/env python3
"""Text rendering of the per-tenant isolation state (docs/tenancy.md).

Fetches ``GET /v1/tenants`` (plus ``GET /v1/slo?tenant=`` burn summaries,
already merged into the document) from a running service and prints a
`top`-style table — the quickest answer to "who is eating the service
right now" without curl+jq gymnastics. ``--watch N`` refreshes every N
seconds until interrupted.

    python scripts/tenant-top.py [--url http://localhost:50081]
        [--watch SECONDS]
"""

from __future__ import annotations

import argparse
import sys
import time

import httpx


def fmt_bytes(n: int | float | None) -> str:
    if not n:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.0f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


def render(doc: dict) -> str:
    tenants = doc.get("tenants") or {}
    lines = []
    header = (
        f"{'TENANT':<18} {'WEIGHT':>6} {'INFL':>4} {'QUEUED':>6} "
        f"{'WAIT':>7} {'ADMIT':>7} {'SHED':>6} {'CPU s':>8} "
        f"{'BYTES':>9} {'SESS':>4} {'BURN':<10}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for label in sorted(tenants):
        row = tenants[label]
        config = row.get("config") or {}
        admission = row.get("admission") or {}
        usage = row.get("usage") or {}
        slo = row.get("slo") or {}
        sheds = admission.get("sheds") or {}
        moved = (
            (usage.get("uploaded_bytes") or 0)
            + (usage.get("downloaded_bytes") or 0)
            + (usage.get("workspace_bytes") or 0)
        )
        if slo.get("fast_burn_alerting"):
            burn = "** PAGE **"
        elif slo.get("alerting"):
            burn = "ALERT"
        elif slo:
            burn = f"{slo.get('error_budget_remaining_ratio', 1.0):.0%} left"
        else:
            burn = "-"
        lines.append(
            f"{label:<18} {config.get('weight') or '-':>6} "
            f"{admission.get('in_flight', 0):>4} "
            f"{admission.get('queued', 0):>6} "
            f"{admission.get('queue_wait_avg_ms', 0.0):>5.1f}ms "
            f"{admission.get('admitted', 0):>7} "
            f"{sum(sheds.values()):>6} "
            f"{usage.get('cpu_s', 0.0):>8.2f} "
            f"{fmt_bytes(moved):>9} "
            f"{row.get('sessions', 0):>4} {burn:<10}"
        )
        if sheds:
            lines.append(
                "  " + "  ".join(f"shed[{k}]={v}" for k, v in sorted(sheds.items()))
            )
    if not tenants:
        lines.append("(no tenants recorded yet)")
    unknown = doc.get("unknown_ids", 0)
    overflow = doc.get("unknown_overflow", 0)
    if unknown or overflow:
        lines.append(
            f"unknown tenant ids: {unknown} tracked"
            + (f", {overflow} collapsed into 'other'" if overflow else "")
        )
    return "\n".join(lines)


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render GET /v1/tenants as a text table."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    parser.add_argument(
        "--watch",
        type=float,
        default=0,
        metavar="SECONDS",
        help="refresh every N seconds until interrupted (0 = one shot)",
    )
    args = parser.parse_args()
    base = args.url.rstrip("/")
    try:
        with httpx.Client(timeout=10.0) as client:
            while True:
                try:
                    response = client.get(f"{base}/v1/tenants")
                    if response.status_code == 501:
                        print(
                            "tenant-top: no tenant registry wired into "
                            f"{base}",
                            file=sys.stderr,
                        )
                        return 1
                    print(render(response.raise_for_status().json()))
                except httpx.HTTPError as e:
                    print(
                        f"tenant-top: cannot reach {base}: {e}",
                        file=sys.stderr,
                    )
                    if args.watch <= 0:
                        return 1
                if args.watch <= 0:
                    return 0
                time.sleep(args.watch)
                print(f"\n--- {time.strftime('%H:%M:%S')} ---")
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
