#!/usr/bin/env python3
"""Text rendering of the fleet state (docs/observability.md).

Fetches ``GET /v1/fleet`` (plus ``GET /v1/slo`` and optionally the recent
lifecycle events) from a running service and prints a `top`-style table —
the quickest answer to "what is the pool doing right now" without curl+jq
gymnastics. ``--watch N`` refreshes every N seconds until interrupted.

    python scripts/fleet-top.py [--url http://localhost:50081] [--events N]
        [--watch SECONDS]
"""

from __future__ import annotations

import argparse
import sys
import time

import httpx


def fmt_age(seconds: float | None) -> str:
    if seconds is None:
        return "-"
    if seconds < 60:
        return f"{seconds:.1f}s"
    if seconds < 3600:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


def render_snapshot(snap: dict) -> str:
    lines = []
    by_state = ", ".join(
        f"{state}={count}" for state, count in sorted(snap["by_state"].items())
    ) or "empty"
    lines.append(
        f"fleet: {snap['live']} live ({by_state})  "
        f"utilization={snap['utilization']:.0%}  "
        f"executions_total={snap['executions_total']}"
        + ("  ** DRAINING **" if snap.get("draining") else "")
    )
    sup = snap.get("supervisor")
    if sup:
        lines.append(
            "supervisor: "
            + ("running" if sup.get("running") else "stopped")
            + f"  last_sweep={fmt_age(sup.get('last_sweep_age_s'))} ago"
            + f"  sweeps={sup.get('sweeps', 0)}"
            + f"  reaped={sup.get('reaped', 0)}"
            + f"  watchdog_kills={sup.get('watchdog_kills', 0)}"
            + f"  inflight={sup.get('inflight', 0)}"
        )
    lifetime = snap.get("lifetime", {})
    lines.append(
        "lifetime: "
        + "  ".join(
            f"{state}={lifetime.get(state, 0)}"
            for state in ("spawning", "ready", "released", "reaped", "failed")
        )
    )
    lines.append("")
    header = (
        f"{'POD':<28} {'STATE':<10} {'AGE':>7} {'SPAWN':>8} {'WORKERS':>7} "
        f"{'EXECS':>5}  {'SESSION':<22} {'LEASE':>7}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for pod in snap["pods"]:
        spawn = f"{pod['spawn_s'] * 1000:.0f}ms" if pod.get("spawn_s") else "-"
        # Leased sandboxes show their owner session + lease age so an
        # operator can tell a busy REPL from a stuck pod (docs/sessions.md);
        # EXECS counts executions inside the lease.
        session = pod.get("session") or "-"
        lease_age = fmt_age(pod.get("lease_age_s")) if pod.get("session") else "-"
        lines.append(
            f"{pod['pod']:<28} {pod['state']:<10} {fmt_age(pod['age_s']):>7} "
            f"{spawn:>8} {pod['workers']:>7} {pod['executions']:>5}  "
            f"{session:<22} {lease_age:>7}"
        )
    if not snap["pods"]:
        lines.append("(no live sandboxes)")
    tenants = snap.get("tenants")
    if tenants:
        # Tenant mix (docs/tenancy.md): who this replica has been serving —
        # the signal a placement-aware router reads off /v1/fleet.
        lines.append(
            "tenants: "
            + "  ".join(
                f"{name}={count}" for name, count in sorted(tenants.items())
            )
        )
    accel = snap.get("accelerator")
    if accel:
        # Accelerator summary (docs/observability.md "Accelerator
        # observability"): compile/retrace totals + HBM headroom — the
        # placement signal the FleetRouter reads off this same field.
        hbm = accel.get("hbm") or {}
        live = hbm.get("live_bytes")
        limit = hbm.get("limit_bytes")
        hbm_part = (
            f"hbm={live / (1 << 20):.1f}MiB" if live is not None else "hbm=-"
        )
        if limit:
            hbm_part += f"/{limit / (1 << 20):.1f}MiB"
        if hbm.get("estimated"):
            hbm_part += " (estimated)"
        lines.append(
            f"accelerator: mesh={accel.get('mesh') or '1'}"
            f"  compiles={accel.get('compiles', 0)}"
            f"  retraces={accel.get('retraces', 0)}"
            f"  {hbm_part}"
        )
    sess = snap.get("sessions")
    if sess:
        lines.append(
            f"sessions: {sess['active']}/{sess['max']} leased"
            + (
                "  ended: "
                + ", ".join(
                    f"{k}={v}" for k, v in sorted(sess["ended_by_reason"].items())
                )
                if sess.get("ended_by_reason")
                else ""
            )
        )
    return "\n".join(lines)


def render_slo(slo: dict) -> str:
    """One summary line per service from ``GET /v1/slo``: error budget left
    and the fast burn rates, with a shout when any alert pair is firing."""
    objectives = slo.get("objectives") or []
    if not objectives:
        return "slo: (no objectives declared)"
    parts = []
    for o in objectives:
        windows = o["windows"]
        sep = "@" if o.get("threshold_ms") is not None else " "
        label = f"{o['name']}{sep}{o['target'] * 100:g}%"
        parts.append(
            f"{label}: budget={o['error_budget_remaining_ratio']:.0%} left"
            f" burn 5m={windows['5m']['burn_rate']:.2f}"
            f" 1h={windows['1h']['burn_rate']:.2f}"
            f" 6h={windows['6h']['burn_rate']:.2f}"
        )
    line = "slo: " + "  |  ".join(parts)
    if slo.get("fast_burn_alerting"):
        line += "  ** FAST BURN — PAGE **"
    elif slo.get("alerting"):
        line += "  ** BURN ALERT **"
    return line


def render_capacity(autoscale: dict) -> str:
    """One capacity line from ``GET /v1/autoscale`` (docs/autoscaling.md):
    demand vs forecast, current→target pool size, and the last scaling
    decision with its reason."""
    if not autoscale:
        return "capacity: (no capacity tracker wired)"
    demand = autoscale.get("demand") or {}
    forecast = autoscale.get("forecast") or {}
    line = (
        f"capacity: demand={demand.get('rps_10s', 0):.1f}rps"
        f" forecast={forecast.get('forecast_rps', 0):.1f}rps"
        f" (horizon {forecast.get('horizon_s', 0):.1f}s)"
        f" warm_pop={demand.get('warm_pop_ratio_60s', 1.0):.0%}"
    )
    if autoscale.get("mode") is not None:
        line += (
            f"  pool {autoscale.get('current_size', 0)}"
            f"->{autoscale.get('target', 0)}"
            f" mode={autoscale['mode']}"
        )
        last = autoscale.get("last_decision")
        if last:
            line += (
                f"  last={last.get('direction')}"
                f" {last.get('from')}->{last.get('to')}"
                f" ({last.get('reason')})"
            )
    else:
        line += "  (no pool autoscaler: local backend)"
    return line


def render_loop(health: dict) -> str:
    """One event-loop health line from ``GET /healthz?verbose=1`` — a
    stalled loop makes every other number in this view lie by omission."""
    mon = health.get("loop")
    if not mon:
        return "loop: (no monitor wired)"
    line = (
        f"loop: lag_last={mon.get('last_lag_ms', 0):.1f}ms"
        f" lag_max={mon.get('max_lag_ms', 0):.1f}ms"
        f" probes={mon.get('probes', 0)}"
        f" stalls={mon.get('stalls', 0)}"
    )
    stall = mon.get("last_stall")
    if stall:
        line += (
            f"  ** LAST STALL {stall.get('lag_s', 0) * 1000:.0f}ms"
            f" ({stall.get('tasks', {}).get('count', 0)} tasks captured) **"
        )
    return line


def render_events(events: list[dict]) -> str:
    lines = ["", f"recent events (newest first, {len(events)}):"]
    for e in events:
        line = f"  {e['pod']:<28} -> {e['state']:<9}"
        if e.get("spawn_s") is not None:
            line += f" spawn={e['spawn_s'] * 1000:.0f}ms"
        if e.get("reason"):
            line += f" reason={e['reason']}"
        if e.get("detail"):
            line += f" ({e['detail']})"
        lines.append(line)
    return "\n".join(lines)


def render_once(client: httpx.Client, base: str, events: int) -> None:
    snap = client.get(f"{base}/v1/fleet").raise_for_status().json()
    print(render_snapshot(snap))
    try:
        # Older replicas without /v1/slo degrade to the no-objectives line.
        slo = client.get(f"{base}/v1/slo").raise_for_status().json()
    except httpx.HTTPError:
        slo = {}
    print(render_slo(slo))
    try:
        # Older replicas without /v1/autoscale degrade to the no-tracker line.
        autoscale = (
            client.get(f"{base}/v1/autoscale").raise_for_status().json()
        )
    except httpx.HTTPError:
        autoscale = {}
    print(render_capacity(autoscale))
    try:
        health = (
            client.get(f"{base}/healthz", params={"verbose": "1"})
            .raise_for_status()
            .json()
        )
    except httpx.HTTPError:
        health = {}
    print(render_loop(health))
    if events > 0:
        event_list = (
            client.get(f"{base}/v1/fleet/events", params={"limit": events})
            .raise_for_status()
            .json()["events"]
        )
        print(render_events(event_list))


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render GET /v1/fleet (+ /v1/slo) as a text table."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    parser.add_argument(
        "--events",
        type=int,
        default=0,
        metavar="N",
        help="also show the last N lifecycle events",
    )
    parser.add_argument(
        "--watch",
        type=float,
        default=0,
        metavar="SECONDS",
        help="refresh every N seconds until interrupted (0 = one shot)",
    )
    args = parser.parse_args()
    base = args.url.rstrip("/")
    try:
        with httpx.Client(timeout=10.0) as client:
            while True:
                try:
                    render_once(client, base, args.events)
                except httpx.HTTPError as e:
                    print(f"fleet-top: cannot reach {base}: {e}", file=sys.stderr)
                    if args.watch <= 0:
                        return 1
                if args.watch <= 0:
                    return 0
                time.sleep(args.watch)
                print(f"\n--- {time.strftime('%H:%M:%S')} ---")
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
