#!/usr/bin/env python3
"""One-shot text rendering of the fleet state (docs/observability.md).

Fetches ``GET /v1/fleet`` (and optionally the recent lifecycle events) from
a running service and prints a `top`-style table — the quickest answer to
"what is the pool doing right now" without curl+jq gymnastics.

    python scripts/fleet-top.py [--url http://localhost:50081] [--events N]
"""

from __future__ import annotations

import argparse
import sys

import httpx


def fmt_age(seconds: float | None) -> str:
    if seconds is None:
        return "-"
    if seconds < 60:
        return f"{seconds:.1f}s"
    if seconds < 3600:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


def render_snapshot(snap: dict) -> str:
    lines = []
    by_state = ", ".join(
        f"{state}={count}" for state, count in sorted(snap["by_state"].items())
    ) or "empty"
    lines.append(
        f"fleet: {snap['live']} live ({by_state})  "
        f"utilization={snap['utilization']:.0%}  "
        f"executions_total={snap['executions_total']}"
        + ("  ** DRAINING **" if snap.get("draining") else "")
    )
    sup = snap.get("supervisor")
    if sup:
        lines.append(
            "supervisor: "
            + ("running" if sup.get("running") else "stopped")
            + f"  last_sweep={fmt_age(sup.get('last_sweep_age_s'))} ago"
            + f"  sweeps={sup.get('sweeps', 0)}"
            + f"  reaped={sup.get('reaped', 0)}"
            + f"  watchdog_kills={sup.get('watchdog_kills', 0)}"
            + f"  inflight={sup.get('inflight', 0)}"
        )
    lifetime = snap.get("lifetime", {})
    lines.append(
        "lifetime: "
        + "  ".join(
            f"{state}={lifetime.get(state, 0)}"
            for state in ("spawning", "ready", "released", "reaped", "failed")
        )
    )
    lines.append("")
    header = f"{'POD':<28} {'STATE':<10} {'AGE':>7} {'SPAWN':>8} {'WORKERS':>7} {'EXECS':>5}"
    lines.append(header)
    lines.append("-" * len(header))
    for pod in snap["pods"]:
        spawn = f"{pod['spawn_s'] * 1000:.0f}ms" if pod.get("spawn_s") else "-"
        lines.append(
            f"{pod['pod']:<28} {pod['state']:<10} {fmt_age(pod['age_s']):>7} "
            f"{spawn:>8} {pod['workers']:>7} {pod['executions']:>5}"
        )
    if not snap["pods"]:
        lines.append("(no live sandboxes)")
    return "\n".join(lines)


def render_events(events: list[dict]) -> str:
    lines = ["", f"recent events (newest first, {len(events)}):"]
    for e in events:
        line = f"  {e['pod']:<28} -> {e['state']:<9}"
        if e.get("spawn_s") is not None:
            line += f" spawn={e['spawn_s'] * 1000:.0f}ms"
        if e.get("reason"):
            line += f" reason={e['reason']}"
        if e.get("detail"):
            line += f" ({e['detail']})"
        lines.append(line)
    return "\n".join(lines)


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render GET /v1/fleet as a one-shot text table."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    parser.add_argument(
        "--events",
        type=int,
        default=0,
        metavar="N",
        help="also show the last N lifecycle events",
    )
    args = parser.parse_args()
    base = args.url.rstrip("/")
    try:
        with httpx.Client(timeout=10.0) as client:
            snap = client.get(f"{base}/v1/fleet").raise_for_status().json()
            print(render_snapshot(snap))
            if args.events > 0:
                events = (
                    client.get(
                        f"{base}/v1/fleet/events",
                        params={"limit": args.events},
                    )
                    .raise_for_status()
                    .json()["events"]
                )
                print(render_events(events))
    except httpx.HTTPError as e:
        print(f"fleet-top: cannot reach {base}: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
