#!/usr/bin/env python3
"""End-to-end chaos smoke: the resilience stack under injected faults, no
cluster required (docs/resilience.md).

Drives the REAL KubernetesCodeExecutor against the in-repo fake cluster
(tests/fakes.py) with scripted faults (tests/chaos.py), wrapped by the real
ResilientCodeExecutor / AdmissionController — i.e. the exact production
wiring minus kubectl. Scenarios:

  1. healthy path         — execute through a (fake) pod, stdout round-trip
  2. deadline bound       — 10 s spawn hang vs a 0.5 s edge deadline
  3. breaker + fallback   — spawn failures trip the breaker; requests degrade
                            to the local executor; cooldown half-opens and
                            the breaker closes on a healthy probe
  4. admission shedding   — in-flight + queue full -> immediate shed
  5. replay               — a pod dies mid-execute; the request transparently
                            replays on a fresh sandbox and still succeeds
  6. supervisor + watchdog— a dead warm sandbox is reaped as unhealthy_idle
                            and the pool replenished; a hung execute is
                            watchdog-killed and fails transient
  7. graceful drain       — draining rejects new work while in-flight work
                            finishes inside the grace window
  8. telemetry export     — the OTLP exporter ships spans to a (fake)
                            collector, which is then killed mid-run: the
                            exporter degrades to bounded drops (queue never
                            grows past its cap, the request path is not
                            slowed) and every trace that missed the
                            collector is accounted in
                            bci_telemetry_dropped_total
  9. edge analysis gate   — a flood of syntax-broken (and policy-denied)
                            submissions through the REAL HTTP edge leaves
                            the warm pool untouched: zero checkouts, pool
                            depth and executions_total unchanged, and every
                            refusal accounted in
                            bci_analysis_rejections_total{rule}
 10. sessions under chaos — a streaming client vanishes mid-chunk (the
                            lease survives and is reaped by the TTL sweep),
                            a sandbox dies mid-lease (the session ends as
                            reaped/died_mid_lease and the pool refills),
                            and a stateless stream whose pod dies delivers
                            a terminal error event — with
                            bci_session_expirations_total accounting every
                            lease end exactly
 11. flight recorder        — wide events flow to the collector as OTLP
                            logs; the collector is killed and the event
                            ring saturated mid-load: request latency is
                            unchanged, and emitted == exported +
                            dropped{reason} exactly for the logs signal
 12. serving saturation   — the serving engine is driven past queue
                            capacity (and through an admission capacity
                            race) while executor requests keep flowing:
                            every bounce lands exactly once in the
                            kind="serving" wide events AND the
                            bci_serving_* counters AND the monitor totals,
                            and the executor path's latency is unchanged
 13. autoscale 10x step   — a 10x arrival-rate step under a manual clock:
                            mode=act pre-spawns within one forecast
                            horizon (warm_pop_ratio back >= 0.95) while
                            mode=off keeps paying cold spawns; sheds stay
                            inside the SLO error budget; every scale
                            decision lands exactly once in the decision
                            log, the kind="autoscale" wide events, and
                            bci_autoscale_decisions_total
 14. fleet router kill    — 3 COMPLETE in-process replicas (real HTTP edge
                            + pool + sessions + SLO each) over one shared
                            snapshot root, fronted by the real FleetRouter;
                            the replica holding leases drains and is then
                            killed mid-load: consistent-hash affinity stays
                            >= 90% warm, every live lease migrates
                            (checkpoint -> re-lease -> restore through
                            shared storage, same client-visible session
                            id), zero lease-scoped 5xx after the kill, the
                            survivors' SLO page alerts stay silent, and the
                            routing/migration accounting agrees exactly
                            across the decision totals, the wide events,
                            and bci_router_* (docs/fleet.md)
 15. abusive tenant        — one tenant floods 100x its rate quota through
                            the REAL HTTP edge over the fake-pod stack
                            (weighted-fair admission + per-tenant quotas,
                            docs/tenancy.md): the other tenants' p50 stays
                            within 10% of baseline, ZERO of their requests
                            shed, their SLO-slice burn alerts stay silent,
                            and the abuser's sheds are accounted exactly
                            once across bci_tenant_shed_total, the wide
                            events, and /v1/tenants
 16. fleet-wide tenancy    — 3 COMPLETE replicas behind 2 peered router
                            edges (docs/fleet.md "Fleet-wide tenancy"):
                            tenant-aware rendezvous placement pins a
                            weight-1 abuser to a single-replica subset,
                            replicas lease fleet-wide quota slices from
                            the routers, and one router edge is KILLED
                            mid-flood: the keyless 100x-quota abuser is
                            held <= 1.2x the fleet-wide quota, victims'
                            p50 stays within 10% with zero sheds, the
                            session created through the dead edge keeps
                            serving through the survivor (pin gossip,
                            zero lease-scoped 5xx), and sheds/leases are
                            accounted exactly across /v1/tenants, the
                            wide events, and bci_tenant_shed_total

Exits nonzero if any scenario misbehaves. Usage:

    python scripts/chaos_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bee_code_interpreter_tpu.config import Config  # noqa: E402
from bee_code_interpreter_tpu.observability import (  # noqa: E402
    TelemetryExporter,
    Tracer,
)
from bee_code_interpreter_tpu.resilience import (  # noqa: E402
    AdmissionController,
    AdmissionRejected,
    BreakerState,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    DrainController,
    HedgingExecutor,
    PoolSupervisor,
    ResilientCodeExecutor,
    RetryPolicy,
    SandboxTransientError,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (  # noqa: E402
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.services.local_code_executor import (  # noqa: E402
    LocalCodeExecutor,
)
from bee_code_interpreter_tpu.services.storage import Storage  # noqa: E402
from bee_code_interpreter_tpu.utils.metrics import Registry  # noqa: E402
from tests.chaos import ChaosKubectl, Fail, FaultPlan, Hang, ManualClock  # noqa: E402
from tests.fakes import FakeCollector, FakeExecutorPods  # noqa: E402

PASS, FAIL = "PASS", "FAIL"
failures: list[str] = []


def report(name: str, ok: bool, detail: str = "") -> None:
    print(f"[{PASS if ok else FAIL}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        failures.append(name)


def dump_fleet(tag: str, executor) -> None:
    """Print the stack's fleet event journal (docs/observability.md) so a
    chaos run shows WHICH sandboxes died and why, not just pass/fail."""
    from bee_code_interpreter_tpu.observability import find_journal

    journal = find_journal(executor)
    events = journal.events()
    print(f"  fleet journal after '{tag}' ({len(events)} events, oldest first):")
    for e in reversed(events):
        line = f"    {e['pod']:<24} -> {e['state']:<9}"
        if e.get("spawn_s") is not None:
            line += f" spawn={e['spawn_s'] * 1000:.0f}ms"
        if e.get("executions") is not None:
            line += f" execs={e['executions']}"
        if e.get("reason"):
            line += f" reason={e['reason']}"
        if e.get("detail"):
            line += f" ({e['detail']})"
        print(line)


def make_stack(tmp: Path, storage, metrics: Registry, clock: ManualClock):
    """One production-shaped stack (fake cluster + real resilience wiring).
    Each scenario gets a fresh one so breaker windows don't bleed across."""
    faults = FaultPlan()
    pods = FakeExecutorPods(tmp / f"pods-{id(faults):x}", faults=faults)
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=0,
        pod_ready_timeout_s=5,
        executor_retry_attempts=1,
    )
    spawn_breaker = CircuitBreaker(
        "k8s-spawn", window=4, failure_rate_threshold=0.5, min_calls=2,
        cooldown_s=30.0, clock=clock,
    )
    k8s = KubernetesCodeExecutor(
        kubectl=ChaosKubectl(pods, faults),
        storage=storage,
        config=config,
        metrics=metrics,
        spawn_breaker=spawn_breaker,
        ip_poll_interval_s=0.02,
    )
    fallback = LocalCodeExecutor(
        storage=storage, workspace_root=tmp / "fallback-ws", disable_dep_install=True
    )
    # Production shape (application_context.py): resilient front over the
    # replay/hedge layer over the pool backend.
    hedged = HedgingExecutor(k8s, replay_max=1, metrics=metrics)
    executor = ResilientCodeExecutor(hedged, fallback=fallback, metrics=metrics)
    return executor, spawn_breaker, faults, pods


async def main() -> int:
    tmp = Path(tempfile.mkdtemp(prefix="chaos-smoke-"))
    storage = Storage(tmp / "objects")
    clock = ManualClock()
    metrics = Registry()
    executor, spawn_breaker, faults, pods = make_stack(tmp, storage, metrics, clock)
    executor2, breaker2, faults2, pods2 = make_stack(tmp, storage, metrics, clock)

    try:
        # 1. healthy path
        result = await executor.execute("print(21 * 2)")
        report("healthy execute via fake pod", result.stdout == "42\n")
        usage = result.usage or {}
        report(
            "execution usage accounted",
            usage.get("cpu_user_s", 0) > 0 and usage.get("wall_s", 0) > 0,
            f"cpu={usage.get('cpu_user_s', 0):.3f}s wall={usage.get('wall_s', 0):.3f}s",
        )
        dump_fleet("healthy path", executor)

        # 2. deadline bounds a hung spawn
        faults.script("pod_wait", Hang(10.0))
        t0 = time.monotonic()
        try:
            await executor.execute("print(1)", deadline=Deadline.after(0.5))
            report("deadline bound over hung spawn", False, "no DeadlineExceeded")
        except DeadlineExceeded:
            elapsed = time.monotonic() - t0
            report(
                "deadline bound over hung spawn",
                elapsed < 0.55,
                f"elapsed {elapsed * 1000:.0f}ms for a 500ms deadline",
            )
        dump_fleet("deadline bound", executor)

        # 3. breaker trips -> fallback serves -> half-open -> closed
        #    (fresh stack: its breaker window starts clean)
        faults2.script("pod_create", Fail("apiserver down"), Fail("apiserver down"))
        for _ in range(2):
            try:
                await executor2.execute("print('down')")
            except RuntimeError:
                pass
        report(
            "breaker opens at failure rate",
            breaker2.state is BreakerState.OPEN,
            f"state={breaker2.state.name}",
        )
        result = await executor2.execute("print('degraded but alive')")
        report(
            "open breaker degrades to local fallback",
            result.stdout == "degraded but alive\n",
        )
        clock.advance(31.0)
        result = await executor2.execute("print('recovered')")
        report(
            "half-open probe recovers to pods",
            result.stdout == "recovered\n"
            and breaker2.state is BreakerState.CLOSED,
            f"state={breaker2.state.name}",
        )
        dump_fleet("breaker + fallback", executor2)

        # 4. admission shedding never hangs
        admission = AdmissionController(
            max_in_flight=1, max_queue=0, retry_after_s=2.0, metrics=metrics
        )
        release = asyncio.Event()

        async def hold():
            async with admission.admit():
                await release.wait()

        holder = asyncio.create_task(hold())
        await asyncio.sleep(0.01)
        t0 = time.monotonic()
        try:
            async with admission.admit():
                pass
            report("admission sheds when full", False, "not shed")
        except AdmissionRejected as e:
            report(
                "admission sheds when full",
                time.monotonic() - t0 < 0.1,
                f"reason={e.reason} retry_after={e.retry_after_s:g}s",
            )
        release.set()
        await holder

        # 5. a pod dies mid-execute -> transparent replay on a fresh sandbox
        #    (fresh stack so breaker windows stay clean)
        executor3, _, faults3, pods3 = make_stack(tmp, storage, metrics, clock)
        k8s3 = executor3.primary.primary  # unwrap resilient -> hedging -> pool
        try:
            faults3.die_mid_execute()
            result = await executor3.execute(
                "print('survived')", deadline=Deadline.after(30)
            )
            report(
                "pod death mid-execute replayed to success",
                result.stdout == "survived\n",
            )
            text = metrics.expose()
            report(
                "replay observable in journal + metrics",
                "bci_execution_replays_total 1" in text
                and any(
                    e.get("reason") == "died_mid_execute"
                    for e in k8s3.journal.events()
                ),
            )
            dump_fleet("replay", executor3)

            # 6a. supervisor reaps a dead warm sandbox and replenishes
            k8s3._config.executor_pod_queue_target_length = 1
            await k8s3.fill_executor_pod_queue()
            victim = k8s3._queue[0]
            for ip in victim.pod_ips:
                await pods3.stop_pod(ip)
            supervisor = PoolSupervisor(
                k8s3, interval_s=60, execute_hard_cap_s=0.2, metrics=metrics
            )
            swept = await supervisor.sweep_once()
            for _ in range(200):  # refill is kicked fire-and-forget
                if k8s3.pool_ready_count == 1:
                    break
                await asyncio.sleep(0.01)
            report(
                "supervisor reaps unhealthy_idle and replenishes",
                swept["reaped"] == 1 and k8s3.pool_ready_count == 1,
                f"reaped={swept['reaped']} ready={k8s3.pool_ready_count}",
            )

            # 6b. a hung execute is watchdog-killed, failing transient
            faults3.hang_execute(30.0)
            request = asyncio.ensure_future(
                executor3.primary.primary.execute("print(1)")
            )
            await asyncio.sleep(0.3)
            swept = await supervisor.sweep_once()
            try:
                await request
                report("watchdog kills hung execute", False, "request succeeded?!")
            except SandboxTransientError as e:
                report(
                    "watchdog kills hung execute",
                    swept["watchdog_killed"] == 1 and "watchdog" in str(e),
                    f"killed={swept['watchdog_killed']}",
                )
            dump_fleet("supervisor + watchdog", executor3)
        finally:
            await pods3.close()

        # 7. graceful drain: in-flight finishes, new work rejected
        drain = DrainController(metrics=metrics, retry_after_s=1.0)
        release = asyncio.Event()

        async def inflight_request():
            with drain.track():
                await release.wait()
                return "finished"

        inflight = asyncio.create_task(inflight_request())
        await asyncio.sleep(0.01)
        drain.begin()
        report(
            "drain rejects new work while tracking in-flight",
            drain.draining and drain.in_flight == 1,
            f"in_flight={drain.in_flight}",
        )
        grace_expired = not await drain.wait_idle(0.05)
        release.set()
        drained = await drain.wait_idle(5.0)
        report(
            "drain waits for in-flight work within the grace",
            grace_expired and drained and await inflight == "finished",
        )

        # 8. telemetry export survives its collector dying mid-run
        #    (fresh registry so the drop accounting is exact)
        m8 = Registry()
        tracer = Tracer(metrics=m8)
        collector = await FakeCollector().start()
        exporter = TelemetryExporter(
            collector.endpoint, m8,
            flush_interval_s=0.05, queue_max=8, batch_max=4,
            retry=RetryPolicy(attempts=2, wait_min_s=0.01, wait_max_s=0.02),
        )
        tracer.add_sink(exporter.enqueue_trace)
        exporter.start()
        executor4, _, _, pods4 = make_stack(tmp, storage, m8, clock)
        enqueued = 0
        try:
            async def traced_execute(tag: str) -> float:
                nonlocal enqueued
                t0 = time.monotonic()
                with tracer.trace("/v1/execute"):
                    result = await executor4.execute(f"print('{tag}')")
                assert result.stdout == f"{tag}\n"
                enqueued += 1
                return time.monotonic() - t0

            pre = [await traced_execute(f"pre{i}") for i in range(3)]
            for _ in range(200):  # the background loop flushes every 50ms
                if collector.span_trace_ids():
                    break
                await asyncio.sleep(0.02)
            report(
                "exporter ships spans while the collector is up",
                len(collector.span_trace_ids()) >= 1,
                f"{len(collector.span_trace_ids())} trace(s) received",
            )

            await collector.stop()  # chaos: collector dies mid-run
            post = [await traced_execute(f"post{i}") for i in range(8)]
            report(
                "collector death leaves the request path unaffected",
                exporter.queue_depth <= 8
                and max(post) < max(max(pre) * 3, max(pre) + 0.3),
                f"queue={exporter.queue_depth}/8 "
                f"pre_max={max(pre) * 1000:.0f}ms "
                f"post_max={max(post) * 1000:.0f}ms",
            )

            await exporter.stop()
            counters = m8.metrics["bci_telemetry_exported_total"]._values
            exported = counters.get((("signal", "traces"),), 0)
            dropped = sum(
                v
                for k, v in m8.metrics[
                    "bci_telemetry_dropped_total"
                ]._values.items()
                if ("signal", "traces") in k
            )
            report(
                "every lost batch accounted in bci_telemetry_dropped_total",
                exported + dropped + exporter.queue_depth == enqueued,
                f"exported={exported:g} dropped={dropped:g} "
                f"queued={exporter.queue_depth} of {enqueued} traces",
            )
        finally:
            await pods4.close()

        # 9. edge analysis gate: a flood of doomed submissions never touches
        #    the warm pool (fresh registry for exact rejection accounting)
        from aiohttp.test_utils import TestClient, TestServer

        from bee_code_interpreter_tpu.analysis import (
            PolicyEngine,
            WorkloadAnalyzer,
        )
        from bee_code_interpreter_tpu.api.http_server import create_http_server
        from bee_code_interpreter_tpu.services.custom_tool_executor import (
            CustomToolExecutor,
        )

        m9 = Registry()
        executor9, _, _, pods9 = make_stack(tmp, storage, m9, clock)
        k8s9 = executor9.primary.primary  # unwrap resilient -> hedging -> pool
        try:
            k8s9._config.executor_pod_queue_target_length = 2
            await k8s9.fill_executor_pod_queue()
            ready_before = k8s9.pool_ready_count
            execs_before = k8s9.journal.executions_total
            app = create_http_server(
                code_executor=executor9,
                custom_tool_executor=CustomToolExecutor(code_executor=executor9),
                metrics=m9,
                analyzer=WorkloadAnalyzer(
                    PolicyEngine(deny_imports=("socket",)), metrics=m9
                ),
            )
            client = TestClient(TestServer(app))
            await client.start_server()
            n_syntax, n_deny = 24, 8
            try:
                statuses_ok = True
                for i in range(n_syntax):
                    resp = await client.post(
                        "/v1/execute",
                        json={"source_code": f"def broken{i}(:\n"},
                    )
                    body = await resp.json()
                    statuses_ok &= (
                        resp.status == 200
                        and body["exit_code"] == 1
                        and "SyntaxError" in body["stderr"]
                    )
                for _ in range(n_deny):
                    resp = await client.post(
                        "/v1/execute", json={"source_code": "import socket\n"}
                    )
                    statuses_ok &= resp.status == 422
            finally:
                await client.close()
            report(
                "doomed flood answered without a sandbox",
                statuses_ok,
                f"{n_syntax} syntax fail-fasts + {n_deny} policy denies",
            )
            report(
                "warm pool untouched by the flood",
                k8s9.pool_ready_count == ready_before
                and k8s9.journal.executions_total == execs_before,
                f"ready={k8s9.pool_ready_count} (was {ready_before}), "
                f"executions_total={k8s9.journal.executions_total}",
            )
            rejections = m9.metrics["bci_analysis_rejections_total"]._values
            syntax_n = rejections.get((("rule", "syntax"),), 0)
            deny_n = rejections.get((("rule", "import:socket"),), 0)
            report(
                "every refusal accounted in bci_analysis_rejections_total",
                syntax_n == n_syntax and deny_n == n_deny,
                f"syntax={syntax_n:g}/{n_syntax} import:socket={deny_n:g}/{n_deny}",
            )
            dump_fleet("edge analysis gate", executor9)
        finally:
            await pods9.close()

        # 10. sessions under chaos: vanished stream client, sandbox death
        #     mid-lease, terminal error events, exact lease accounting
        from bee_code_interpreter_tpu.sessions import (
            SessionManager,
            streamed_events,
        )

        m10 = Registry()
        executor10, _, faults10, pods10 = make_stack(tmp, storage, m10, clock)
        k8s10 = executor10.primary.primary  # unwrap resilient -> hedging -> pool
        try:
            k8s10._config.executor_pod_queue_target_length = 1
            await k8s10.fill_executor_pod_queue()
            sessions10 = SessionManager(
                k8s10, storage, max_sessions=2, ttl_s=0.6, idle_s=10.0,
                metrics=m10,
            )

            # 10a. client vanishes mid-stream: the lease survives the
            #      disconnect and the TTL sweep reaps it later.
            session_a = await sessions10.create()
            chunks_seen = asyncio.Event()

            async def first_chunk(_kind, _text):
                chunks_seen.set()

            vanish = asyncio.ensure_future(
                sessions10.execute(
                    session_a.session_id,
                    "import time\nprint('c1', flush=True)\ntime.sleep(20)\n",
                    on_event=first_chunk,
                )
            )
            await asyncio.wait_for(chunks_seen.wait(), timeout=10)
            vanish.cancel()  # the "client" is gone
            try:
                await vanish
            except asyncio.CancelledError:
                pass
            report(
                "vanished stream client leaves the lease alive",
                sessions10.active_count == 1,
                f"active={sessions10.active_count}",
            )
            await asyncio.sleep(0.7)  # past the 0.6s TTL
            expired = await sessions10.sweep_once()
            for _ in range(200):  # the reap kicks a refill fire-and-forget
                if k8s10.pool_ready_count >= 1:
                    break
                await asyncio.sleep(0.01)
            ttl_events = [
                e
                for e in k8s10.journal.events()
                if e["state"] == "lease_expired" and e.get("reason") == "ttl"
            ]
            report(
                "abandoned lease reaped on TTL and the pool refilled",
                expired == 1
                and len(ttl_events) == 1
                and k8s10.pool_ready_count >= 1,
                f"expired={expired} ready={k8s10.pool_ready_count}",
            )

            # 10b. the sandbox dies mid-lease: the session ends as
            #      reaped/died_mid_lease and the pool refills.
            session_b = await sessions10.create()
            faults10.die_mid_execute()
            try:
                await sessions10.execute(session_b.session_id, "print('x')")
                report("sandbox death mid-lease surfaces", False, "succeeded?!")
            except SandboxTransientError:
                died_events = [
                    e
                    for e in k8s10.journal.events()
                    if e["state"] == "reaped"
                    and e.get("reason") == "died_mid_lease"
                ]
                report(
                    "sandbox death mid-lease ends the session as reaped",
                    sessions10.active_count == 0 and len(died_events) == 1,
                    f"active={sessions10.active_count}",
                )

            # 10c. a stateless stream whose pod dies mid-run delivers a
            #      terminal error event (never a silent hang).
            faults10.die_mid_execute()

            async def run_stream(on_event):
                return await k8s10.execute_stream(
                    "print('doomed')", on_event=on_event
                )

            events = [item async for item in streamed_events(run_stream)]
            report(
                "mid-stream pod death yields a terminal error event",
                bool(events) and events[-1].get("event") == "error",
                f"terminal={events[-1].get('event') if events else None}",
            )

            # 10d. exact accounting: every lease end has exactly one reason.
            ends = m10.metrics["bci_session_expirations_total"]._values
            ttl_n = ends.get((("reason", "ttl"),), 0)
            died_n = ends.get((("reason", "sandbox_died"),), 0)
            report(
                "every lease end accounted in bci_session_expirations_total",
                ttl_n == 1 and died_n == 1 and sum(ends.values()) == 2,
                f"ttl={ttl_n:g} sandbox_died={died_n:g} total={sum(ends.values()):g}",
            )
            dump_fleet("sessions under chaos", executor10)
        finally:
            await pods10.close()

        # 11. flight recorder: wide events as OTLP logs; dead collector +
        #     saturated ring mid-load degrade to exactly-accounted drops
        #     with the request path untouched (fresh registry for exact
        #     accounting).
        from bee_code_interpreter_tpu.observability import FlightRecorder

        m11 = Registry()
        tracer11 = Tracer(metrics=m11)
        recorder11 = FlightRecorder(max_events=16, metrics=m11)
        tracer11.add_sink(recorder11.record_trace)
        collector11 = await FakeCollector().start()
        exporter11 = TelemetryExporter(
            collector11.endpoint, m11,
            flush_interval_s=0.05, queue_max=8, batch_max=4,
            retry=RetryPolicy(attempts=2, wait_min_s=0.01, wait_max_s=0.02),
        )
        recorder11.add_sink(exporter11.enqueue_log)
        exporter11.start()
        executor11, _, _, pods11 = make_stack(tmp, storage, m11, clock)
        try:
            async def wide_execute(tag: str) -> float:
                t0 = time.monotonic()
                with tracer11.trace("/v1/execute"):
                    result = await executor11.execute(f"print('{tag}')")
                assert result.stdout == f"{tag}\n"
                return time.monotonic() - t0

            pre = [await wide_execute(f"wide{i}") for i in range(3)]
            for _ in range(200):  # the background loop flushes every 50ms
                if collector11.log_records():
                    break
                await asyncio.sleep(0.02)
            records = collector11.log_records()
            report(
                "wide events reach the collector as OTLP logs",
                len(records) >= 1
                and '"kind": "request"' in records[0]["body"]["stringValue"],
                f"{len(records)} log record(s) received",
            )

            await collector11.stop()  # chaos: collector dies mid-run
            # saturate the ring + logs queue with a burst of synthetic
            # events while real requests keep flowing
            for i in range(40):
                recorder11.record({"kind": "request", "outcome": "ok", "n": i})
            post = [await wide_execute(f"after{i}") for i in range(6)]
            report(
                "saturated ring + dead collector leave latency unchanged",
                exporter11.logs_queue_depth <= 8
                and len(recorder11) <= 16
                and max(post) < max(max(pre) * 3, max(pre) + 0.3),
                f"logs_queue={exporter11.logs_queue_depth}/8 "
                f"ring={len(recorder11)}/16 "
                f"pre_max={max(pre) * 1000:.0f}ms "
                f"post_max={max(post) * 1000:.0f}ms",
            )

            await exporter11.stop()
            emitted = recorder11.snapshot()["emitted"]
            logs_exported = m11.metrics[
                "bci_telemetry_exported_total"
            ]._values.get((("signal", "logs"),), 0)
            logs_dropped = sum(
                v
                for k, v in m11.metrics[
                    "bci_telemetry_dropped_total"
                ]._values.items()
                if ("signal", "logs") in k
            )
            report(
                "every wide event accounted across the logs signal",
                logs_exported + logs_dropped + exporter11.logs_queue_depth
                == emitted,
                f"exported={logs_exported:g} dropped={logs_dropped:g} "
                f"queued={exporter11.logs_queue_depth} of {emitted} emitted",
            )
        finally:
            await pods11.close()

        # 12. serving saturation: the continuous-batching engine is driven
        #     past queue capacity and through an admission capacity race
        #     while executor requests keep flowing — every bounce accounted
        #     exactly once across wide events / counters / monitor totals,
        #     executor-path latency unchanged (fresh registry, tiny CPU
        #     model; docs/observability.md "Serving observability").
        import dataclasses

        import numpy as np
        import jax
        import jax.numpy as jnp

        from bee_code_interpreter_tpu.models import transformer as T
        from bee_code_interpreter_tpu.models.engine import Engine
        from bee_code_interpreter_tpu.models.serving import ContinuousBatcher
        from bee_code_interpreter_tpu.observability import (
            ServingMonitor,
            TraceStore,
        )

        m12 = Registry()
        recorder12 = FlightRecorder(max_events=64, metrics=m12)
        monitor12 = ServingMonitor(
            metrics=m12, store=TraceStore(), recorder=recorder12
        )
        cfg12 = dataclasses.replace(
            T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
        )
        batcher12 = ContinuousBatcher(
            T.init_params(cfg12, jax.random.PRNGKey(0)), cfg12,
            max_batch=2, n_pages=16, page_size=4, max_pages_per_seq=4,
            metrics=m12,
        )
        engine12 = Engine(batcher12, max_queue=2, metrics=m12)
        monitor12.attach(engine12)
        executor12, _, _, pods12 = make_stack(tmp, storage, m12, clock)
        try:
            async def timed_execute(tag: str) -> float:
                t0 = time.monotonic()
                result = await executor12.execute(f"print('{tag}')")
                assert result.stdout == f"{tag}\n"
                return time.monotonic() - t0

            pre = [await timed_execute(f"calm{i}") for i in range(3)]

            long_prompt = [
                int(x)
                for x in np.random.default_rng(7).integers(0, 200, 9)
            ]
            # one admission capacity race: queue-level credit says go, the
            # batcher's own page arithmetic says no -> requeue, not failure
            tickets = [engine12.submit(long_prompt, 4)]
            real_credit = batcher12.prefix_credit
            free_backup = batcher12.free_pages
            batcher12.prefix_credit = lambda prompt, adapter: 10_000
            batcher12.free_pages = []
            engine12._admit_ready()
            batcher12.prefix_credit = real_credit
            batcher12.free_pages = free_backup

            tickets.append(engine12.submit([5, 3, 7, 2], 4))
            rejections = 0
            for _ in range(3):  # queue full (2): every further submit bounces
                try:
                    engine12.submit([1, 2, 3], 4)
                except RuntimeError:
                    rejections += 1
            # decode runs off-loop while executor requests keep flowing
            decode = asyncio.create_task(
                asyncio.to_thread(engine12.run_to_completion)
            )
            during = [await timed_execute(f"busy{i}") for i in range(6)]
            await decode
            ok = all(len(engine12.result(t)) == 4 for t in tickets)

            snap12 = monitor12.snapshot()
            events12 = recorder12.events(kind="serving", limit=100)
            by_name = {
                name: len([e for e in events12 if e["name"] == name])
                for name in (
                    "serving.reject", "serving.requeue", "serving.request"
                )
            }
            text12 = m12.expose()
            report(
                "every serving bounce accounted exactly once",
                ok
                and rejections == 3
                and by_name["serving.reject"] == 3
                and by_name["serving.requeue"] == 1
                and by_name["serving.request"] == 2
                and snap12["totals"]["rejected"] == 3
                and snap12["totals"]["requeued"] == 1
                and snap12["totals"]["finished"] == 2
                and "bci_serving_queue_rejected_total 3" in text12
                and "bci_serving_requeues_total 1" in text12,
                f"events={by_name} totals={snap12['totals']}",
            )
            report(
                "executor-path latency unchanged under serving saturation",
                max(during) < max(max(pre) * 3, max(pre) + 0.3),
                f"pre_max={max(pre) * 1000:.0f}ms "
                f"during_max={max(during) * 1000:.0f}ms",
            )
        finally:
            await pods12.close()

        # 13. capacity loop under a 10x arrival step (docs/autoscaling.md):
        #     the REAL executor + supervisor + autoscaler over fake pods,
        #     driven by a manual clock. mode=act pre-spawns within one
        #     forecast horizon (warm_pop_ratio recovers >= 0.95) while
        #     mode=off keeps paying cold spawns; sheds stay inside the SLO
        #     error budget; every decision lands exactly once in the
        #     decision log, the kind="autoscale" wide events, and
        #     bci_autoscale_decisions_total.
        from bee_code_interpreter_tpu.observability import (
            DemandTracker,
            Forecaster,
            SloEngine,
            parse_objectives,
        )
        from bee_code_interpreter_tpu.resilience import PoolAutoscaler

        BURST13, STEP13 = 6, 4

        async def drive_surge13(mode: str) -> dict:
            clock13 = ManualClock(2000.0)
            m13 = Registry()
            recorder13 = FlightRecorder(max_events=64)
            demand13 = DemandTracker(clock=clock13, metrics=m13)
            forecaster13 = Forecaster(demand13)
            slo13 = SloEngine(parse_objectives(99.5, None), clock=clock13)
            admission13 = AdmissionController(
                max_in_flight=32, max_queue=0, metrics=m13, demand=demand13
            )
            faults13 = FaultPlan()
            pods13 = FakeExecutorPods(
                tmp / f"pods13-{mode}", faults=faults13
            )
            k8s13 = KubernetesCodeExecutor(
                kubectl=ChaosKubectl(pods13, faults13),
                storage=storage,
                config=Config(
                    executor_backend="kubernetes",
                    executor_port=pods13.port,
                    executor_pod_queue_target_length=2,
                    pod_ready_timeout_s=5,
                    executor_retry_attempts=1,
                ),
                metrics=m13,
                ip_poll_interval_s=0.02,
            )
            k8s13.journal.add_sink(demand13.on_fleet_event)
            autoscaler13 = PoolAutoscaler(
                k8s13, forecaster13, demand13,
                mode=mode, min_size=1, max_size=12, idle_s=30.0,
                cooldown_s=0.0, base_target=2, slo=slo13,
                recorder=recorder13, metrics=m13, clock=clock13,
            )
            supervisor13 = PoolSupervisor(
                k8s13, interval_s=60, autoscaler=autoscaler13
            )

            async def one_request() -> None:
                async with admission13.admit():
                    result = await k8s13.execute("print(1)")
                    assert result.stdout == "1\n"
                    slo13.record(ok=True, duration_s=0.01)

            def assigned_counts() -> tuple[int, int]:
                warm = cold = 0
                for e in k8s13.journal.events():
                    if e["state"] == "assigned":
                        if e.get("reason") == "warm_pop":
                            warm += 1
                        else:
                            cold += 1
                return warm, cold

            async def settle() -> None:
                for _ in range(400):
                    if (
                        k8s13.pool_ready_count
                        >= min(k8s13.pool_target, 12)
                        and k8s13.pool_spawning_count == 0
                    ):
                        break
                    await asyncio.sleep(0.01)

            try:
                await k8s13.fill_executor_pod_queue()
                for _ in range(3):  # warm trickle
                    await one_request()
                    await supervisor13.sweep_once()
                    await settle()
                    clock13.advance(1.0)
                ratios = []
                for _ in range(STEP13):  # the 10x step
                    w0, c0 = assigned_counts()
                    await asyncio.gather(
                        *(one_request() for _ in range(BURST13))
                    )
                    w1, _ = assigned_counts()
                    ratios.append((w1 - w0) / BURST13)
                    await supervisor13.sweep_once()
                    await settle()
                    clock13.advance(1.0)
                return {
                    "ratios": ratios,
                    "target": k8s13.pool_target,
                    "override": k8s13.pool_target_override,
                    "decisions": autoscaler13.decisions(),
                    "wide": recorder13.events(kind="autoscale"),
                    "metrics_text": m13.expose(),
                    "sheds": demand13.sheds_total,
                    "arrivals": demand13.arrivals_total,
                    "horizon": forecaster13.horizon_s(),
                    "budget_left": slo13.error_budget_remaining(
                        slo13.objectives[0]
                    ),
                }
            finally:
                await pods13.close()

        act13 = await drive_surge13("act")
        off13 = await drive_surge13("off")
        report(
            "act absorbs the 10x step within one forecast horizon",
            act13["ratios"][0] < 0.95
            and all(r >= 0.95 for r in act13["ratios"][1:])
            and act13["target"] >= BURST13
            and act13["override"] is not None,
            f"per-burst warm ratios {act13['ratios']} "
            f"target={act13['target']} horizon={act13['horizon']:.1f}s",
        )
        report(
            "off keeps paying cold spawns under the same step",
            all(r < 0.95 for r in off13["ratios"])
            and off13["target"] == 2
            and not off13["decisions"],
            f"per-burst warm ratios {off13['ratios']} (static target 2)",
        )
        report(
            "sheds stay inside the SLO error budget",
            act13["sheds"] <= 0.005 * act13["arrivals"]
            and act13["budget_left"] == 1.0,
            f"sheds={act13['sheds']} of {act13['arrivals']} arrivals, "
            f"budget_left={act13['budget_left']:.0%}",
        )
        ids13 = [d["decision_id"] for d in act13["decisions"]]
        counted13 = sum(
            int(line.rsplit(" ", 1)[1])
            for line in act13["metrics_text"].splitlines()
            if line.startswith("bci_autoscale_decisions_total{")
        )
        report(
            "every scale decision accounted exactly once",
            len(ids13) == len(set(ids13))
            and sorted(e["decision_id"] for e in act13["wide"])
            == sorted(ids13)
            and counted13 == len(ids13),
            f"{len(ids13)} decision(s) across log/wide-events/counter",
        )

        # 14. fleet router: kill a replica mid-load — leases migrate, SLO
        #     holds, accounting exact (docs/fleet.md; tier-1 twin in
        #     tests/test_fleet_router.py).
        import httpx

        from aiohttp import web as aioweb

        from bee_code_interpreter_tpu.fleet import FleetRouter, create_router_app
        from tests.fakes import ReplicaStack, free_port

        shared_root = tmp / "shared-objects-14"
        stacks14 = [
            await ReplicaStack(f"r{i}", tmp / "fleet14", shared_root).start()
            for i in range(3)
        ]
        router14 = FleetRouter(
            [(s.name, s.base_url) for s in stacks14],
            refresh_interval_s=0.2,
            dead_after_s=0.5,
        )
        runner14 = aioweb.AppRunner(create_router_app(router14))
        await runner14.setup()
        port14 = free_port()
        await aioweb.TCPSite(runner14, "127.0.0.1", port14).start()
        url14 = f"http://127.0.0.1:{port14}"
        await router14.refresh_once()
        router14.start()
        client14 = httpx.AsyncClient(timeout=30.0)
        try:
            seeds14 = []
            for i in range(3):
                object_id = await stacks14[0].storage.write(
                    f"chain-{i}".encode()
                )
                seeds14.append({"/workspace/seed.txt": object_id})
            landed14: dict[int, set] = {i: set() for i in range(3)}
            for _round in range(4):
                for i, files in enumerate(seeds14):
                    r = await client14.post(
                        f"{url14}/v1/execute",
                        json={
                            "source_code": "print(open('seed.txt').read())",
                            "files": files,
                        },
                    )
                    assert r.status_code == 200, r.text
                    landed14[i].add(
                        router14.recorder.events(kind="routing", limit=1)[0][
                            "replica"
                        ]
                    )
            total_keyed = sum(router14.affinity_totals.values())
            warm_rate = router14.affinity_totals["warm"] / total_keyed
            # The bar is the acceptance criterion (>= 90% warm), not
            # one-replica-per-chain: a sustained-saturation spill is
            # correct behavior on a loaded box.
            report(
                "router keeps repeat traffic >= 90% warm on its ring owner",
                warm_rate >= 0.9,
                f"warm {warm_rate:.0%} over {total_keyed} keyed placements, "
                f"per-chain replicas {[sorted(v) for v in landed14.values()]}",
            )

            sids14 = []
            for i in range(2):
                r = await client14.post(f"{url14}/v1/sessions", json={})
                sid = r.json()["session_id"]
                sids14.append(sid)
                r = await client14.post(
                    f"{url14}/v1/sessions/{sid}/execute",
                    json={
                        "source_code": (
                            f"open('state.txt', 'w').write('state-{i}')\n"
                            "print('ok')"
                        )
                    },
                )
                assert r.status_code == 200, r.text
            victim14 = next(
                s
                for s in stacks14
                if s.name == router14.sessions[sids14[0]].replica
            )
            pinned14 = [
                sid
                for sid in sids14
                if router14.sessions[sid].replica == victim14.name
            ]
            victim14.drain.begin()
            await router14.refresh_once()
            await asyncio.gather(*await router14.evacuate_draining())
            for _ in range(100):  # the background loop may own the handoff
                if all(
                    router14.sessions[sid].replica != victim14.name
                    for sid in pinned14
                ):
                    break
                await asyncio.sleep(0.05)
            migrated14 = [
                sid
                for sid in pinned14
                if router14.sessions[sid].replica != victim14.name
            ]
            report(
                "drain migrates every live lease off the draining replica",
                len(migrated14) == len(pinned14)
                and router14.totals["migrations_ok"] == len(pinned14)
                and router14.totals["migrations_failed"] == 0,
                f"{len(migrated14)}/{len(pinned14)} lease(s) handed off "
                f"from {victim14.name}",
            )

            await victim14.stop(hard=True)
            failures14 = 0
            for i, sid in enumerate(sids14):
                r = await client14.post(
                    f"{url14}/v1/sessions/{sid}/execute",
                    json={"source_code": "print(open('state.txt').read())"},
                )
                if (
                    r.status_code != 200
                    or f"state-{i}" not in r.json()["stdout"]
                    or r.json()["session_id"] != sid
                ):
                    failures14 += 1
            for files in seeds14:
                r = await client14.post(
                    f"{url14}/v1/execute",
                    json={"source_code": "print('alive')", "files": files},
                )
                if r.status_code != 200:
                    failures14 += 1
            survivors14 = [s for s in stacks14 if s.name != victim14.name]
            report(
                "post-kill: sessions serve under their original ids, "
                "stateless traffic re-homes, SLO page silent",
                failures14 == 0
                and all(
                    not s.slo.snapshot()["fast_burn_alerting"]
                    for s in survivors14
                ),
                f"{len(sids14)} session(s) + {len(seeds14)} stateless "
                "requests after the kill, zero failures",
            )

            routing_events14 = router14.recorder.events(
                kind="routing", limit=10_000
            )
            migrate_events14 = router14.recorder.events(
                kind="lease_migrate", limit=10_000
            )
            text14 = router14.metrics.expose()
            counted14 = sum(
                int(line.rsplit(" ", 1)[1])
                for line in text14.splitlines()
                if line.startswith("bci_router_requests_total{")
            )
            migrations_counted14 = sum(
                int(line.rsplit(" ", 1)[1])
                for line in text14.splitlines()
                if line.startswith("bci_router_lease_migrations_total{")
            )
            snap14 = router14.snapshot()
            placed14 = [
                e for e in routing_events14 if e.get("replica") is not None
            ]
            report(
                "routing + migration accounting agrees exactly across "
                "decisions/events/counters",
                len(routing_events14) == router14.totals["routed"]
                and counted14 == router14.totals["routed"]
                and len(migrate_events14)
                == router14.totals["migrations_ok"]
                + router14.totals["migrations_failed"]
                and migrations_counted14 == len(migrate_events14)
                and sum(
                    r["routed_total"] for r in snap14["replicas"]
                )
                == len(placed14),
                f"routed={router14.totals['routed']} events="
                f"{len(routing_events14)} counter={counted14}; "
                f"migrations={len(migrate_events14)}",
            )
            print("  router replica view after the kill:")
            for rep in snap14["replicas"]:
                print(
                    f"    {rep['name']:<4} {rep['state']:<9} "
                    f"util={rep['utilization']:.2f} leases={rep['leases']} "
                    f"ring={rep['ring_share']:.0%} "
                    f"routed={rep['routed_total']} "
                    f"breaker={rep['breaker']}"
                )
        finally:
            await client14.aclose()
            await runner14.cleanup()
            await router14.stop()
            for s in stacks14:
                await s.stop()

        # 15. abusive tenant: 100x-quota flood through the real HTTP edge
        #     over the fake-pod stack — victims provably untouched, abuser
        #     sheds accounted exactly once (docs/tenancy.md; tier-1 twin in
        #     tests/test_tenancy.py).
        import statistics

        from aiohttp.test_utils import TestClient as TClient15
        from aiohttp.test_utils import TestServer as TServer15

        from bee_code_interpreter_tpu.api.http_server import (
            create_http_server as create_http_15,
        )
        from bee_code_interpreter_tpu.observability import (
            FlightRecorder as Recorder15,
        )
        from bee_code_interpreter_tpu.observability import (
            SloEngine as Slo15,
        )
        from bee_code_interpreter_tpu.observability import (
            parse_objectives as parse_objectives_15,
        )
        from bee_code_interpreter_tpu.services.custom_tool_executor import (
            CustomToolExecutor as ToolExec15,
        )
        from bee_code_interpreter_tpu.tenancy import (
            TENANT_HEADER,
            TenantRegistry,
            parse_tenants,
        )

        m15 = Registry()
        faults15 = FaultPlan()
        pods15 = FakeExecutorPods(tmp / "pods15", faults=faults15)
        k8s15 = KubernetesCodeExecutor(
            kubectl=ChaosKubectl(pods15, faults15),
            storage=storage,
            config=Config(
                executor_backend="kubernetes",
                executor_port=pods15.port,
                executor_pod_queue_target_length=2,
                pod_ready_timeout_s=5,
                executor_retry_attempts=1,
            ),
            metrics=m15,
            ip_poll_interval_s=0.02,
        )
        registry15 = TenantRegistry(
            parse_tenants("abuser:weight=1:rps=2:burst=2,victim:weight=4"),
            metrics=m15,
        )
        admission15 = AdmissionController(
            max_in_flight=4, max_queue=8, retry_after_s=0.2,
            metrics=m15, tenancy=registry15,
        )
        slo15 = Slo15(parse_objectives_15(99.5, None), metrics=m15)
        tracer15 = Tracer(metrics=m15)
        recorder15 = Recorder15(max_events=4096, metrics=m15)
        tracer15.add_sink(recorder15.record_trace)
        app15 = create_http_15(
            code_executor=k8s15,
            custom_tool_executor=ToolExec15(code_executor=k8s15),
            metrics=m15,
            admission=admission15,
            request_deadline_s=30.0,
            tracer=tracer15,
            recorder=recorder15,
            slo=slo15,
            tenancy=registry15,
        )
        client15 = TClient15(TServer15(app15))
        await client15.start_server()
        N_ABUSE15 = 200  # 100x the abuser's burst-2 token bucket
        try:
            await k8s15.fill_executor_pod_queue()
            body15 = {"source_code": "print('ok')"}

            async def victim_request() -> float:
                t0 = time.monotonic()
                resp = await client15.post(
                    "/v1/execute", json=body15,
                    headers={TENANT_HEADER: "victim"},
                )
                assert resp.status == 200, await resp.text()
                return time.monotonic() - t0

            baseline15 = []
            for _ in range(15):
                baseline15.append(await victim_request())
                await asyncio.sleep(0.02)
            p50_base15 = statistics.median(baseline15)

            async def abuse15() -> None:
                await client15.post(
                    "/v1/execute", json=body15,
                    headers={TENANT_HEADER: "abuser"},
                )

            flood15 = [
                asyncio.create_task(abuse15()) for _ in range(N_ABUSE15)
            ]
            during15 = []
            for _ in range(15):
                during15.append(await victim_request())
                await asyncio.sleep(0.02)
            await asyncio.gather(*flood15)
            p50_during15 = statistics.median(during15)

            report(
                "victim p50 within 10% under a 100x-quota flood",
                p50_during15 <= p50_base15 * 1.10,
                f"baseline {p50_base15 * 1000:.1f}ms vs "
                f"{p50_during15 * 1000:.1f}ms during the flood",
            )
            victim15 = admission15.tenant_snapshot()["victim"]
            victim_slo15 = slo15.tenant_snapshot("victim")
            report(
                "zero victim sheds and a silent victim SLO slice",
                victim15["sheds"] == {}
                and recorder15.events(outcome="shed", tenant="victim") == []
                and not victim_slo15["alerting"]
                and not victim_slo15["fast_burn_alerting"],
                f"victim sheds={victim15['sheds']}",
            )
            abuser15 = admission15.tenant_snapshot()["abuser"]
            shed15 = sum(abuser15["sheds"].values())
            counter15 = sum(
                v
                for key, v in m15.metrics["bci_tenant_shed_total"]
                ._values.items()
                if ("tenant", "abuser") in key
            )
            wide15 = recorder15.events(
                outcome="shed", tenant="abuser", limit=10_000
            )
            tenants_doc15 = (
                await (await client15.get("/v1/tenants")).json()
            )
            report(
                "abuser sheds accounted exactly once across "
                "counter/wide-events/v1-tenants",
                shed15 > 0
                and shed15 + abuser15["admitted"] == N_ABUSE15
                and counter15 == shed15
                and len(wide15) == shed15
                and tenants_doc15["tenants"]["abuser"]["usage"]["sheds"]
                == shed15,
                f"{shed15} shed of {N_ABUSE15} flood requests "
                f"(counter={counter15:g} wide={len(wide15)})",
            )
        finally:
            await client15.close()
            await k8s15.aclose()
            await pods15.close()

        # 16. fleet-wide tenancy: tenant-aware placement + distributed
        #     quota leases + router HA under a router-edge kill
        #     (docs/fleet.md "Fleet-wide tenancy"; tier-1 twin in
        #     tests/test_fleet_router.py).
        from bee_code_interpreter_tpu.tenancy import (
            TenantRegistry as Registry16,
            parse_tenants as parse_tenants_16,
        )

        spec16 = "abuser:weight=1:rps=2:burst=2,victim:weight=4"
        shared16 = tmp / "shared-objects-16"
        port16a, port16b = free_port(), free_port()
        url16a = f"http://127.0.0.1:{port16a}"
        url16b = f"http://127.0.0.1:{port16b}"
        stacks16 = [
            await ReplicaStack(
                f"r{i}",
                tmp / "fleet16",
                shared16,
                tenants=spec16,
                lease_router_urls=[url16a, url16b],
            ).start()
            for i in range(3)
        ]

        def make_router16(rid, peer_name, peer_url):
            return FleetRouter(
                [(s.name, s.base_url) for s in stacks16],
                refresh_interval_s=0.2,
                dead_after_s=1.0,
                tenancy=Registry16(parse_tenants_16(spec16)),
                peers=[(peer_name, peer_url)],
                quota_ttl_s=1.0,
                router_id=rid,
            )

        router16a = make_router16("A", "b", url16b)
        router16b = make_router16("B", "a", url16a)
        runners16 = []
        for router, port in ((router16a, port16a), (router16b, port16b)):
            runner = aioweb.AppRunner(create_router_app(router))
            await runner.setup()
            await aioweb.TCPSite(runner, "127.0.0.1", port).start()
            await router.refresh_once()
            router.start()
            runners16.append(runner)
        runner16a, runner16b = runners16
        client16 = httpx.AsyncClient(timeout=30.0)
        statuses16: list[int] = []
        try:
            body16 = {"source_code": "print('ok')"}
            r = await client16.post(f"{url16a}/v1/sessions", json={})
            sid16 = r.json()["session_id"]
            r = await client16.post(
                f"{url16a}/v1/sessions/{sid16}/execute",
                json={
                    "source_code": "open('state.txt', 'w').write('sixteen')"
                },
            )
            assert r.status_code == 200, r.text

            async def victim16() -> float:
                t0 = time.monotonic()
                resp = await client16.post(
                    f"{url16b}/v1/execute",
                    json=body16,
                    headers={TENANT_HEADER: "victim"},
                )
                assert resp.status_code == 200, resp.text
                return time.monotonic() - t0

            baseline16 = []
            for _ in range(12):
                baseline16.append(await victim16())
                await asyncio.sleep(0.02)
            p50_base16 = statistics.median(baseline16)

            flood16_start = time.monotonic()

            async def abuse16(base_url) -> None:
                resp = await client16.post(
                    f"{base_url}/v1/execute",
                    json=body16,
                    headers={TENANT_HEADER: "abuser"},
                )
                statuses16.append(resp.status_code)

            wave16 = [
                asyncio.create_task(abuse16(url16a if i % 2 else url16b))
                for i in range(60)
            ]
            during16 = []
            for _ in range(6):
                during16.append(await victim16())
                await asyncio.sleep(0.02)
            await asyncio.gather(*wave16)
            await asyncio.sleep(0.5)  # one gossip + lease-refresh beat

            await runner16a.cleanup()  # kill edge A mid-flood
            await router16a.stop()

            wave16 = [
                asyncio.create_task(abuse16(url16b)) for _ in range(60)
            ]
            for _ in range(6):
                during16.append(await victim16())
                await asyncio.sleep(0.02)
            await asyncio.gather(*wave16)
            elapsed16 = time.monotonic() - flood16_start
            p50_during16 = statistics.median(during16)

            admitted16 = sum(
                s.admission.tenant_snapshot()
                .get("abuser", {})
                .get("admitted", 0)
                for s in stacks16
            )
            abuser16 = router16b._tenancy.get("abuser")
            bound16 = 1.2 * (
                abuser16.rps * elapsed16 + abuser16.burst_depth
            )
            report(
                "keyless 100x abuser held <= 1.2x the FLEET-wide quota",
                1 <= admitted16 <= bound16,
                f"{admitted16} admitted fleet-wide vs bound "
                f"{bound16:.1f} over {elapsed16:.1f}s",
            )
            victim_sheds16 = sum(
                sum(
                    s.admission.tenant_snapshot()
                    .get("victim", {})
                    .get("sheds", {})
                    .values()
                )
                for s in stacks16
            )
            report(
                "victim p50 within 10% and zero victim sheds fleet-wide",
                p50_during16 <= p50_base16 * 1.10 + 0.01
                and victim_sheds16 == 0,
                f"baseline {p50_base16 * 1000:.1f}ms vs "
                f"{p50_during16 * 1000:.1f}ms, {victim_sheds16} shed(s)",
            )

            r = await client16.post(
                f"{url16b}/v1/sessions/{sid16}/execute",
                json={"source_code": "print(open('state.txt').read())"},
            )
            report(
                "session from the DEAD edge keeps serving via gossip "
                "(zero lease-scoped 5xx)",
                r.status_code == 200
                and "sixteen" in r.json().get("stdout", "")
                and r.json().get("session_id") == sid16,
                f"status {r.status_code} via the surviving edge",
            )

            ledger16 = router16b.ledger.snapshot()
            lessees16 = set(
                ledger16["tenants"].get("abuser", {}).get("lessees", {})
            )
            lease16 = next(
                (
                    s.quota_leases.lease("abuser")
                    for s in stacks16
                    if s.name in lessees16
                ),
                None,
            )
            retries16 = router16b.metrics.metrics[
                "bci_router_retries_total"
            ]._values
            total_sheds16 = 0
            exact16 = True
            for s in stacks16:
                lane = s.admission.tenant_snapshot().get("abuser")
                sheds = sum((lane or {}).get("sheds", {}).values())
                total_sheds16 += sheds
                wide = s.recorder.events(
                    outcome="shed", tenant="abuser", limit=10_000
                )
                counter = sum(
                    v
                    for key, v in s.metrics.metrics[
                        "bci_tenant_shed_total"
                    ]._values.items()
                    if ("tenant", "abuser") in key
                )
                doc = (
                    await client16.get(f"{s.base_url}/v1/tenants")
                ).json()
                usage = (
                    doc["tenants"].get("abuser", {}).get("usage") or {}
                )
                exact16 = exact16 and (
                    len(wide) == sheds
                    and counter == sheds
                    and usage.get("sheds", sheds) == sheds
                )
            report(
                "sheds + leases account exactly across "
                "v1-tenants/wide-events/metrics, sticky sheds never "
                "re-walked, single-subset lease on the survivor ledger",
                exact16
                and total_sheds16 == statuses16.count(429)
                and admitted16 + total_sheds16 == len(statuses16)
                and len(lessees16) == 1
                and lease16 is not None
                and retries16.get((("reason", "shed"),), 0) == 0,
                f"{total_sheds16} shed(s), lessees={sorted(lessees16)}",
            )
        finally:
            await client16.aclose()
            await runner16b.cleanup()
            await router16b.stop()
            await router16a.stop()
            for s in stacks16:
                await s.stop()

        # 17. fleet observability plane: one distributed trace through
        #     router + replica, a replica killed mid-traced-request (the
        #     retry walk shows in the trace) and mid-federated-query (the
        #     answer stays partial-valid, never a 500)
        #     (docs/observability.md "Fleet observability"; tier-1 twin in
        #     tests/test_fleet_observability.py).
        from bee_code_interpreter_tpu.fleet import (
            affinity_key as affinity_key_17,
        )

        shared17 = tmp / "shared-objects-17"
        stacks17 = [
            await ReplicaStack(f"r{i}", tmp / "fleet17", shared17).start()
            for i in range(3)
        ]
        router17 = FleetRouter(
            [(s.name, s.base_url) for s in stacks17],
            refresh_interval_s=0.2,
            dead_after_s=0.5,
        )
        runner17 = aioweb.AppRunner(create_router_app(router17))
        await runner17.setup()
        port17 = free_port()
        await aioweb.TCPSite(runner17, "127.0.0.1", port17).start()
        await router17.refresh_once()
        router17.start()
        url17 = f"http://127.0.0.1:{port17}"
        client17 = httpx.AsyncClient(timeout=30.0)
        try:
            object17 = await stacks17[0].storage.write(b"chaos-17")
            files17 = {"/workspace/seed.txt": object17}
            client_trace17 = "beadfeedbeadfeedbeadfeedbeadfeed"
            r = await client17.post(
                f"{url17}/v1/execute",
                json={"source_code": "print('ok')", "files": files17},
                headers={
                    "traceparent": f"00-{client_trace17}-b7ad6b7169203331-01"
                },
            )
            trace17 = (
                await client17.get(f"{url17}/v1/traces/{client_trace17}")
            ).json()
            router_stages17 = set(
                (trace17.get("router") or {}).get("stage_ms") or {}
            )
            replica_sources17 = [
                s for s in trace17.get("sources", []) if s != "router"
            ]
            report(
                "one trace spans router->replica->sandbox, client "
                "traceparent continued",
                r.status_code == 200
                and r.headers.get("X-Trace-Id") == client_trace17
                and {"placement", "breaker", "attempt", "proxy"}
                <= router_stages17
                and len(replica_sources17) == 1
                and bool(
                    trace17["replicas"][replica_sources17[0]]["stage_ms"]
                ),
                f"sources={trace17.get('sources')} "
                f"router stages={sorted(router_stages17)}",
            )

            # Kill the key's OWNER mid-request: the in-flight proxied call
            # dies, the router's retry walk lands the request elsewhere —
            # all of it inside ONE trace.
            owner17 = router17.ring.owner(affinity_key_17(files17))
            victim17 = next(s for s in stacks17 if s.name == owner17)
            task17 = asyncio.create_task(
                client17.post(
                    f"{url17}/v1/execute",
                    json={
                        "source_code": "import time; time.sleep(0.6); print('survived')",
                        "files": files17,
                    },
                )
            )
            await asyncio.sleep(0.25)  # let the proxied call commit
            # An abrupt kill: don't let the dying edge drain the in-flight
            # request gracefully — the router must see the connection die.
            victim17.runner._shutdown_timeout = 0.05
            await victim17.stop(hard=True)
            r = await task17
            mid_trace17 = router17.trace_store.get(
                r.headers.get("X-Trace-Id", "")
            )
            attempts17 = (
                sum(
                    1
                    for s in mid_trace17.spans
                    if s.name == "attempt"
                )
                if mid_trace17 is not None
                else 0
            )
            report(
                "replica killed mid-traced-request: rerouted to a "
                "survivor, retry walk visible in the trace",
                r.status_code == 200
                and "survived" in r.json().get("stdout", "")
                and attempts17 >= 2,
                f"status={r.status_code} attempts={attempts17}",
            )

            # Mid-kill federated query (dead not yet detected), then the
            # settled form: exact {"name": "dead"} accounting, never a 500.
            bundle17 = await client17.get(f"{url17}/v1/fleet/debug/bundle")
            mid_ok17 = (
                bundle17.status_code == 200
                and owner17 in bundle17.json()["replicas_failed"]
            )
            deadline17 = time.monotonic() + 5.0
            while time.monotonic() < deadline17:
                states17 = {
                    rep["name"]: rep["state"]
                    for rep in router17.snapshot()["replicas"]
                }
                if states17.get(owner17) == "dead":
                    break
                await asyncio.sleep(0.05)
            slo17 = (await client17.get(f"{url17}/v1/slo")).json()
            survivors17 = sorted(
                s.name for s in stacks17 if s.name != owner17
            )
            report(
                "federated SLO/bundle survive the kill with exact "
                "partial accounting",
                mid_ok17
                and slo17["replicas_failed"] == {owner17: "dead"}
                and sorted(slo17["replicas_reporting"]) == survivors17
                and sorted(slo17["fleet"]) == survivors17,
                f"failed={slo17['replicas_failed']} "
                f"reporting={slo17['replicas_reporting']}",
            )
        finally:
            await client17.aclose()
            await runner17.cleanup()
            await router17.stop()
            for s in stacks17:
                await s.stop()

        # 19. accelerator observability: a forced retrace during a live
        #     serving request is accounted exactly once — ONE kind="compile"
        #     wide event per new executable, the bci_compile_total{retrace}
        #     counter, a backdated xla.compile span inside the REQUEST's
        #     trace, and GET-/v1/accelerator-shape totals all agreeing on
        #     the same numbers and the same trace_id
        #     (docs/observability.md "Accelerator observability"; scenario
        #     18 is the capacity flash crowd in tests/test_chaos_capacity.py).
        from bee_code_interpreter_tpu.observability import DeviceMonitor

        m19 = Registry()
        recorder19 = FlightRecorder(max_events=256, metrics=m19)
        store19 = TraceStore()
        monitor19 = ServingMonitor(
            metrics=m19, store=store19, recorder=recorder19
        )
        device19 = DeviceMonitor(metrics=m19, recorder=recorder19)
        batcher19 = ContinuousBatcher(
            T.init_params(cfg12, jax.random.PRNGKey(0)), cfg12,
            max_batch=2, n_pages=16, page_size=4, max_pages_per_seq=4,
            metrics=m19,
        )
        engine19 = Engine(batcher19, max_queue=4, metrics=m19)
        monitor19.attach(engine19)
        device19.attach(engine19)

        # Request A: first contact — everything compiles as first_call.
        t19a = engine19.submit([1, 2, 3], 4)
        await asyncio.to_thread(engine19.run_to_completion)
        first_calls19 = recorder19.events(kind="compile", limit=100)
        baseline_retraces19 = device19.snapshot()["compile"]["by_trigger"].get(
            "retrace", 0
        )

        # Request B: a longer prompt pads to MORE pages -> a new prefill
        # shape -> XLA retraces while the request is live.
        t19b = engine19.submit([5, 3, 7, 2, 9, 11], 4)
        await asyncio.to_thread(engine19.run_to_completion)
        ok19 = (
            len(engine19.result(t19a)) == 4
            and len(engine19.result(t19b)) == 4
        )

        retrace_events19 = [
            e
            for e in recorder19.events(kind="compile", limit=100)
            if e["trigger"] == "retrace"
        ]
        snap19 = device19.snapshot()
        n_retraces19 = len(retrace_events19) - baseline_retraces19
        text19 = m19.expose()
        counter19 = 0
        for line in text19.splitlines():
            if line.startswith('bci_compile_total{trigger="retrace"}'):
                counter19 = int(float(line.split()[-1]))
        trace_ids19 = {e.get("trace_id") for e in retrace_events19}
        # the retrace happened during ONE live request: every retrace event
        # names that request's trace, and that trace holds the compile span
        tid19 = next(iter(trace_ids19), None)
        trace19 = store19.get(tid19) if tid19 else None
        compile_spans19 = [
            s
            for s in (trace19.spans if trace19 is not None else [])
            if s.name == "xla.compile"
        ]
        report(
            "forced retrace during live serving accounted exactly once "
            "across event/counter/span/snapshot, one trace_id",
            ok19
            and n_retraces19 >= 1
            and len(first_calls19) >= 1
            and all(
                e["trigger"] == "first_call" for e in first_calls19
            )
            and counter19 == len(retrace_events19)
            and snap19["compile"]["by_trigger"].get("retrace", 0)
            == len(retrace_events19)
            and snap19["compile"]["total"]
            == len(recorder19.events(kind="compile", limit=100))
            and len(trace_ids19) == 1
            and tid19 is not None
            and len(compile_spans19) == len(retrace_events19),
            f"retraces={n_retraces19} counter={counter19} "
            f"trace_ids={trace_ids19} spans={len(compile_spans19)}",
        )

        text = metrics.expose()
        wanted = [
            "bci_executor_fallback_total 1",
            'bci_breaker_transitions_total{breaker="k8s-spawn",to="open"}',
            'bci_admission_shed_total{reason="queue_full"} 1',
            "bci_execution_replays_total 1",
            'bci_pod_reaped_total{reason="unhealthy_idle"} 1',
            'bci_pod_reaped_total{reason="hung_execute"} 1',
            "bci_supervisor_probe_seconds_count 2",
        ]
        missing = [w for w in wanted if w not in text]
        report("resilience counters in /metrics", not missing, str(missing or "all present"))
    finally:
        await pods.close()
        await pods2.close()

    print()
    if failures:
        print(f"chaos smoke FAILED: {len(failures)} scenario(s): {failures}")
        return 1
    print(
        "chaos smoke passed: deadline, breaker, fallback, admission, replay, "
        "supervisor, watchdog, drain, telemetry export, edge analysis gate, "
        "sessions-under-chaos, flight-recorder-logs, serving-saturation, "
        "autoscale-10x-step, fleet-router-kill, abusive-tenant, "
        "fleet-wide-tenancy, fleet-observability, accelerator-compile "
        "all behaved"
    )
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
