#!/usr/bin/env python3
"""Run the edge workload analyzer on a file or stdin (docs/analysis.md).

The exact pass both API edges run before a submission can touch a warm
sandbox: syntax fail-fast, policy findings, and the dep prediction — so an
operator can dry-run a policy (or a user can see why the edge refused
their code) without submitting anything.

Usage:

    python scripts/analyze.py payload.py
    cat payload.py | python scripts/analyze.py -
    python scripts/analyze.py payload.py --json
    python scripts/analyze.py payload.py --deny-imports socket,ctypes \\
        --deny-calls "subprocess,os.fork" --warn-calls "raw_socket"
    python scripts/analyze.py --self-lint        # run the repo asynclint
    python scripts/analyze.py --concurrency-lint # the await-aware lint
    python scripts/analyze.py --jax-lint         # the accelerator-stack lint
    python scripts/analyze.py --contract-lint    # the cross-transport lint
    python scripts/analyze.py --surface > docs/api_surface.json  # the golden
    python scripts/analyze.py --self-lint --sarif > asynclint.sarif

scripts/lint.sh chains all four self-lints plus the metrics/docs lints —
the one command CI needs. ``--sarif`` renders any self-lint as a SARIF
2.1.0 log (suppressed findings carried with their justifications).
``--surface`` dumps the extracted API surface model (docs/analysis.md
"Contract lint") in the exact checked-in golden format.

Without explicit --deny/--warn flags the policy comes from the same
APP_POLICY_* environment the service reads, so a dry run matches what the
deployed edge would decide. Exit codes: 0 clean (warnings included),
1 syntax error, 2 policy deny, 3 self-lint violations.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bee_code_interpreter_tpu.analysis import (  # noqa: E402
    PolicyEngine,
    inspect_source,
    split_patterns,
)
from bee_code_interpreter_tpu.config import Config  # noqa: E402


def build_policy(args: argparse.Namespace) -> PolicyEngine:
    flags = (
        args.deny_imports, args.warn_imports, args.deny_calls,
        args.warn_calls, args.deny_paths, args.warn_paths,
        args.dynamic_import,
    )
    if any(f is not None for f in flags):
        return PolicyEngine(
            deny_imports=split_patterns(args.deny_imports),
            warn_imports=split_patterns(args.warn_imports),
            deny_calls=split_patterns(args.deny_calls),
            warn_calls=split_patterns(args.warn_calls),
            deny_paths=split_patterns(args.deny_paths),
            warn_paths=split_patterns(args.warn_paths),
            dynamic_import=args.dynamic_import or "warn",
        )
    return PolicyEngine.from_config(Config.from_env())


def render_table(rows: list[tuple[str, ...]], header: tuple[str, ...]) -> str:
    widths = [
        max(len(r[i]) for r in [header, *rows]) for i in range(len(header))
    ]
    def fmt(row: tuple[str, ...]) -> str:
        return "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
    lines = [fmt(header), fmt(tuple("-" * w for w in widths))]
    lines += [fmt(r) for r in rows]
    return "\n".join(lines)


def _render_lint(report, tool_name: str, as_json: bool, as_sarif: bool) -> int:
    if as_sarif:
        from bee_code_interpreter_tpu.analysis import sarif_log, tool_run

        print(
            json.dumps(
                sarif_log(
                    [tool_run(tool_name, report.violations, report.suppressed)]
                )
            )
        )
    elif as_json:
        print(
            json.dumps(
                {
                    "violations": [vars(v) for v in report.violations],
                    "suppressed": [
                        {**vars(v), "reason": s.reason}
                        for v, s in report.suppressed
                    ],
                    "stale_suppressions": [
                        vars(s) for s in report.stale_suppressions
                    ],
                }
            )
        )
    else:
        print(report.summary())
        if report.suppressed:
            print(f"({len(report.suppressed)} suppressed with justification)")
    return 0 if report.clean else 3


def self_lint(as_json: bool, as_sarif: bool = False) -> int:
    from bee_code_interpreter_tpu.analysis import lint_paths

    return _render_lint(lint_paths(), "asynclint", as_json, as_sarif)


def concurrency_lint(as_json: bool, as_sarif: bool = False) -> int:
    from bee_code_interpreter_tpu.analysis import lint_concurrency_paths

    return _render_lint(
        lint_concurrency_paths(), "concurrencylint", as_json, as_sarif
    )


def jax_lint(as_json: bool, as_sarif: bool = False) -> int:
    from bee_code_interpreter_tpu.analysis import lint_jax_paths

    return _render_lint(lint_jax_paths(), "jaxlint", as_json, as_sarif)


def contract_lint(as_json: bool, as_sarif: bool = False) -> int:
    from bee_code_interpreter_tpu.analysis import lint_contract_paths

    return _render_lint(
        lint_contract_paths(), "contractlint", as_json, as_sarif
    )


def dump_surface() -> int:
    from bee_code_interpreter_tpu.analysis import surface_json

    # sort_keys + trailing newline: byte-identical to the checked-in
    # golden, so `--surface > docs/api_surface.json` is the whole update
    # workflow (docs/analysis.md "Updating the surface golden").
    print(json.dumps(surface_json(), indent=2, sort_keys=True))
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Edge workload analyzer (docs/analysis.md)"
    )
    parser.add_argument("source", nargs="?", help="file to analyze, or - for stdin")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument("--self-lint", action="store_true",
                        help="run the repo asynclint instead of analyzing a payload")
    parser.add_argument("--concurrency-lint", action="store_true",
                        help="run the await-aware concurrency lint "
                             "(analysis/concurrencylint.py)")
    parser.add_argument("--jax-lint", action="store_true",
                        help="run the accelerator-stack lint over models/ "
                             "ops/ parallel/ runtime/shim/ "
                             "(analysis/jaxlint.py)")
    parser.add_argument("--contract-lint", action="store_true",
                        help="run the cross-transport API-contract lint "
                             "over the HTTP/gRPC/router edges "
                             "(analysis/contractlint.py)")
    parser.add_argument("--surface", action="store_true",
                        help="dump the extracted API surface model in the "
                             "docs/api_surface.json golden format")
    parser.add_argument("--sarif", action="store_true",
                        help="render a self-lint as SARIF 2.1.0 (implies "
                             "machine-readable output)")
    for flag in ("deny-imports", "warn-imports", "deny-calls", "warn-calls",
                 "deny-paths", "warn-paths"):
        parser.add_argument(f"--{flag}", default=None,
                            help=f"comma-separated {flag.replace('-', ' ')} patterns")
    parser.add_argument("--dynamic-import", default=None,
                        choices=("off", "warn", "deny"),
                        help="what a non-constant-foldable import target "
                             "means (default: warn)")
    args = parser.parse_args()

    if args.self_lint:
        return self_lint(args.json, args.sarif)
    if args.concurrency_lint:
        return concurrency_lint(args.json, args.sarif)
    if args.jax_lint:
        return jax_lint(args.json, args.sarif)
    if args.contract_lint:
        return contract_lint(args.json, args.sarif)
    if args.surface:
        return dump_surface()
    if not args.source:
        parser.error(
            "source file (or -) required unless "
            "--self-lint/--concurrency-lint/--jax-lint/--contract-lint/"
            "--surface"
        )

    source = (
        sys.stdin.read()
        if args.source == "-"
        else Path(args.source).read_text()
    )
    inspection = inspect_source(source)
    if inspection.syntax_error is not None:
        if args.json:
            print(json.dumps({"syntax_error": inspection.syntax_error}))
        else:
            sys.stderr.write(inspection.syntax_error)
        return 1

    policy = build_policy(args)
    if inspection.analysis_error is not None:
        # Mirror the deployed edge exactly (the docstring's promise):
        # unanalyzable + declared policy = fail-closed deny.
        findings = policy.unanalyzable_findings(inspection.analysis_error)
    else:
        findings = policy.evaluate(inspection)
    # The edge ships None ("no claim; the pod scans itself") for
    # unanalyzable source — distinct from [] ("scanned, install nothing").
    deps = (
        None if inspection.analysis_error is not None
        else inspection.predicted_deps
    )
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    "imports": sorted(inspection.imports),
                    "predicted_deps": deps,
                }
            )
        )
    else:
        if findings:
            print(
                render_table(
                    [(f.severity, f.rule, f.message) for f in findings],
                    ("severity", "rule", "message"),
                )
            )
        else:
            print("no policy findings")
        print(
            "predicted deps: "
            + (
                "(no claim — unanalyzable; the sandbox scans itself)"
                if deps is None
                else ", ".join(deps) or "(none)"
            )
        )
    return 2 if any(f.severity == "deny" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
