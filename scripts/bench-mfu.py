#!/usr/bin/env python
"""Flagship-model MFU and decode tokens/sec THROUGH THE SERVICE PATH.

bench.py's headline is a raw matmul chain; this measures the transformer
library itself (VERDICT r3 next-round #3): a ~0.8B llama-shaped config
(fits one v5e chip's 16 GB HBM with f32 masters + AdamW moments) driven
via the same sandbox-executor path as /v1/execute —

1. ``mfu_train``: one full train step (forward + backward + AdamW update),
   timed as an N-step lax.scan chain inside one jit (params carry the data
   dependency; a single scalar readback — the RTT-proof structure every
   bench in this repo uses). MFU = achieved flops / v5e bf16 peak, with
   flops/step = (6·P + 12·n_layers·L·d_model)·B·L — the standard
   PaLM-appendix accounting (6N for the dense params fwd+bwd, the second
   term for attention score/value matmuls, causal already folded).
2. ``service_decode``: KV-cached greedy decode tokens/sec on the same
   config through the same path (bench-decode.py measures decode
   in-process; this is the service-path row for the BASELINE table).

Successful measurements land in TPU_EVIDENCE.jsonl. Exits 2 without a TPU.

The reference publishes no model-perf numbers at all (SURVEY §6) — this
script exists because the rebuild's own bar is a *measured* table.
"""

from __future__ import annotations

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# v5e single-chip bf16 peak (matches BASELINE.md's 185 TF ≈ 94%-of-peak
# bookkeeping for the matmul headline).
V5E_BF16_PEAK_FLOPS = 197e12

# ~0.8B params: embed+head 2·(32000·2048)=131M·2, 12 layers of
# (attn 10.5M + swiglu 34.6M); f32 masters + AdamW m,v ≈ 9.7 GB.
CONFIG = dict(vocab_size=32000, d_model=2048, n_layers=12, n_heads=16,
              n_kv_heads=4, d_ff=5632, max_seq_len=2048)
B, L = 4, 1024
N_TRAIN = 8  # train-step chain length (each step ~0.1 s at 50% MFU)
B_DEC, L_PROMPT, N_DEC = 8, 128, 64

def build_payload(CONFIG=CONFIG, B=B, L=L, N_TRAIN=N_TRAIN, B_DEC=B_DEC,
                  L_PROMPT=L_PROMPT, N_DEC=N_DEC) -> str:
    """The in-sandbox source, parameterized so tests can run a tiny-config
    variant through the identical mechanics on CPU."""
    return f"""
import time
import jax, jax.numpy as jnp, optax
from jax import lax
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig, Transformer, forward, decode_step,
    init_decode_cache, init_params, loss_fn,
)
from bee_code_interpreter_tpu.utils.benchclock import chain_diff

config = TransformerConfig(**{CONFIG!r})
B, L = {B}, {L}
params = init_params(config, jax.random.PRNGKey(0))
n_params = sum(x.size for x in jax.tree.leaves(params))
optimizer = optax.adamw(3e-4)
opt_state = optimizer.init(params)
seq = jax.random.randint(jax.random.PRNGKey(1), (B, L + 1), 0, config.vocab_size)
batch = {{"tokens": seq[:, :-1], "targets": seq[:, 1:]}}

def train_chain(n_steps):
    @jax.jit
    def f(params, opt_state, batch):
        def step(carry, _):
            params, opt_state = carry
            grads = jax.grad(loss_fn)(params, batch, config)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), None
        (params, _), _ = lax.scan(step, (params, opt_state), None, length=n_steps)
        return params["ln_f"].astype(jnp.float32).sum()
    return f

def best_of(f, *args, reps=2):
    float(f(*args))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best

t_n = best_of(train_chain({N_TRAIN}), params, opt_state, batch)
t_1 = best_of(train_chain(1), params, opt_state, batch)
per_step = chain_diff(t_n, t_1, {N_TRAIN}, "train")
# 6N counts only MATMUL params: the embedding table is a gather (no
# matmul flops), so it is excluded; the untied lm_head IS a matmul and
# stays. Counting the embed would inflate MFU ~10% at this config.
n_matmul_params = n_params - config.vocab_size * config.d_model
flops_per_step = (
    6 * n_matmul_params + 12 * config.n_layers * L * config.d_model
) * B * L
print(f"RESULT_TRAIN {{per_step * 1e3:.2f}} {{flops_per_step / per_step / 1e12:.4f}} {{n_params}}")

# --- decode tokens/sec on the same config -------------------------------
Bd, Lp = {B_DEC}, {L_PROMPT}
prompt = jax.random.randint(jax.random.PRNGKey(2), (Bd, Lp), 0, config.vocab_size)
logits, (k_pre, v_pre) = forward(params, prompt, config, None, return_kv=True)
cache0 = init_decode_cache(config, Bd, Lp + {N_DEC} + 1, k_pre, v_pre)
first = jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)

def decode_chain(n_steps):
    @jax.jit
    def f(tok, cache):
        def body(carry, pos):
            tok, cache = carry
            lg, cache = decode_step(params, tok, pos, cache, config)
            nxt = jnp.argmax(lg[:, -1:, :], axis=-1).astype(jnp.int32)
            return (nxt, cache), None
        (tok, _), _ = lax.scan(
            body, (tok, cache),
            jnp.arange(Lp, Lp + n_steps, dtype=jnp.int32),
        )
        return tok.astype(jnp.float32).sum()
    return f

t_dn = best_of(decode_chain({N_DEC}), first, cache0)
t_d1 = best_of(decode_chain(1), first, cache0)
per_tok = chain_diff(t_dn, t_d1, {N_DEC}, "decode")
print(f"RESULT_DECODE {{per_tok * 1e3:.3f}} {{Bd / per_tok:.1f}}")
"""


def _parse_results(stdout: str) -> dict[str, list[float]]:
    out: dict[str, list[float]] = {}
    for line in stdout.splitlines():
        for marker in ("RESULT_TRAIN", "RESULT_DECODE"):
            if line.startswith(marker):
                out[marker] = [float(tok) for tok in line.split()[1:]]
    missing = [m for m in ("RESULT_TRAIN", "RESULT_DECODE") if m not in out]
    if missing:
        raise RuntimeError(f"no {missing} in payload stdout: {stdout!r}")
    return out


def _emit_results(emit, results: dict[str, list[float]], via: str) -> None:
    per_step_ms, achieved_tflops, n_params = results["RESULT_TRAIN"][:3]
    emit("mfu_train", {
        "config": {**CONFIG, "batch": B, "seq_len": L,
                   "params": int(n_params)},
        "per_step_ms": round(per_step_ms, 1),
        "achieved_tflops": round(achieved_tflops, 1),
        "mfu": round(achieved_tflops * 1e12 / V5E_BF16_PEAK_FLOPS, 3),
        "peak_flops": V5E_BF16_PEAK_FLOPS,
        "optimizer": "adamw",
        "via": via,
    })
    per_tok_ms, toks_per_sec = results["RESULT_DECODE"][:2]
    emit("service_decode" if via.startswith("service") else "mfu_decode", {
        "config": {**CONFIG, "batch": B_DEC, "prompt_len": L_PROMPT},
        "per_step_ms": round(per_tok_ms, 3),
        "tokens_per_sec": round(toks_per_sec, 1),
        "via": via,
    })


def run_inprocess(emit) -> None:
    """The same train-MFU + decode payload, exec'd INSIDE an
    already-initialized jax process — scripts/tpu-oneshot.py's one-client
    battery path. The ``via`` field says in-process so it can never be
    mistaken for the service-path row; main() (the service-path run) is
    attempted separately when the tunnel tolerates more than one client."""
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        exec(compile(build_payload(), "<mfu-payload>", "exec"),
             {"__name__": "__mfu_payload__"})
    _emit_results(emit, _parse_results(buf.getvalue()),
                  via="in-process one-client battery")


def main() -> None:
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    probe = bench.probe_tpu()
    if not probe.get("ok") or probe.get("platform") != "tpu":
        print(f"no TPU: {probe}", file=sys.stderr)
        sys.exit(2)

    import asyncio
    import functools

    from bee_code_interpreter_tpu.utils import evidence

    emit = functools.partial(evidence.emit, script="scripts/bench-mfu.py")

    results = asyncio.run(
        bench.run_payload_multi(
            build_payload(), {}, 1200.0, ("RESULT_TRAIN", "RESULT_DECODE")
        )
    )
    _emit_results(emit, results, via="service execution path")


if __name__ == "__main__":
    main()
