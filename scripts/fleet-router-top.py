#!/usr/bin/env python3
"""Text rendering of the fleet-router state (docs/fleet.md).

Fetches ``GET /v1/fleet/replicas`` from a running router edge and prints a
`top`-style per-replica table — utilization, SLO burn, leases, hash-ring
ownership share, breaker state, routed totals — plus the router's session
pins, decision/affinity/migration tallies, each replica's tenant and
cost-class mix, the fleet-wide quota-lease ledger, and peer-router health
(docs/fleet.md "Fleet-wide tenancy"). When the router serves the federated
``GET /v1/slo`` surface (docs/observability.md "Fleet observability") a
fleet SLO line and federation health (replicas reporting/failed) render
too. ``--watch N`` refreshes every N seconds until interrupted.

    python scripts/fleet-router-top.py [--url http://localhost:50080]
        [--watch SECONDS]
"""

from __future__ import annotations

import argparse
import sys
import time

import httpx


def fmt_age(seconds: float | None) -> str:
    if seconds is None:
        return "-"
    if seconds < 60:
        return f"{seconds:.1f}s"
    if seconds < 3600:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


def fmt_mix(mix: dict) -> str:
    """``{"alpha": 12, "beta": 3}`` -> ``alpha=12 beta=3``, largest first."""
    items = sorted(mix.items(), key=lambda kv: (-kv[1], kv[0]))
    return " ".join(f"{k}={v}" for k, v in items) or "-"


def render_slo(slo: dict | None) -> list[str]:
    """The fleet SLO line + federation health from the router's federated
    ``GET /v1/slo`` (docs/observability.md "Fleet observability"); empty
    when the router predates the federated surface."""
    if not slo:
        return []
    lines = []
    burn = "PAGE" if slo.get("fast_burn_alerting") else (
        "ticket" if slo.get("alerting") else "ok"
    )
    fleet_burn = "PAGE" if slo.get("fleet_fast_burn") else (
        "ticket" if slo.get("fleet_alerting") else "ok"
    )
    budget = "-"
    for objective in slo.get("objectives") or []:
        if objective.get("kind") == "availability":
            remaining = objective.get("error_budget_remaining_ratio")
            if isinstance(remaining, (int, float)):
                budget = f"{remaining:.0%}"
            break
    lines.append(
        f"slo: edge budget_remaining={budget} burn={burn}  "
        f"fleet burn={fleet_burn}"
    )
    reporting = slo.get("replicas_reporting")
    failed = slo.get("replicas_failed") or {}
    if reporting is not None:
        failed_str = (
            " ".join(f"{n}={failed[n]}" for n in sorted(failed)) or "-"
        )
        lines.append(
            f"federation: reporting={len(reporting)} "
            f"failed={len(failed)} ({failed_str})"
        )
    return lines


def render(snap: dict, slo: dict | None = None) -> str:
    lines = []
    replicas = snap.get("replicas", [])
    by_state: dict[str, int] = {}
    for replica in replicas:
        by_state[replica["state"]] = by_state.get(replica["state"], 0) + 1
    state_summary = (
        ", ".join(f"{s}={c}" for s, c in sorted(by_state.items())) or "empty"
    )
    totals = snap.get("totals", {})
    affinity = snap.get("affinity", {})
    sessions = snap.get("sessions", {})
    lines.append(
        f"router: {len(replicas)} replica(s) ({state_summary})  "
        f"routed={totals.get('routed', 0)}  "
        f"retries={totals.get('retries', 0)}  "
        f"pinned_sessions={sessions.get('pinned', 0)}"
    )
    keyed = affinity.get("warm", 0) + affinity.get("spill", 0)
    warm_rate = affinity.get("warm", 0) / keyed if keyed else None
    lines.append(
        "placement: "
        + "  ".join(
            f"{k}={affinity.get(k, 0)}"
            for k in ("warm", "spill", "keyless", "tenant")
        )
        + (f"  warm_rate={warm_rate:.0%}" if warm_rate is not None else "")
        + f"  migrations ok={totals.get('migrations_ok', 0)}"
        + f" failed={totals.get('migrations_failed', 0)}"
    )
    peers = snap.get("peers", [])
    if peers:
        lines.append(
            "peers: "
            + "  ".join(
                f"{p['name']}={'up' if p.get('up') else 'DOWN'}"
                + (f"({p['last_error']})" if p.get("last_error") else "")
                for p in peers
            )
        )
    lines.extend(render_slo(slo))
    lines.append("")
    header = (
        f"{'REPLICA':<12} {'STATE':<9} {'UTIL':>5} {'BURN':>5} "
        f"{'LEASES':>6} {'PODS':>5} {'RING':>5} {'ROUTED':>7} "
        f"{'BREAKER':<9} {'SEEN':>6}  ERROR"
    )
    lines.append(header)
    by_replica = sessions.get("by_replica", {})
    for replica in replicas:
        lines.append(
            f"{replica['name']:<12} "
            f"{replica['state'] + ('*' if replica.get('cordoned') else ''):<9} "
            f"{replica['utilization']:>5.0%} "
            f"{'PAGE' if replica.get('slo_fast_burn') else 'ok':>5} "
            f"{by_replica.get(replica['name'], replica.get('leases', 0)):>6} "
            f"{str(replica.get('ready_pods', 0)) + '/' + str(replica.get('live_pods', 0)):>5} "
            f"{replica.get('ring_share', 0.0):>5.0%} "
            f"{replica.get('routed_total', 0):>7} "
            f"{replica.get('breaker', '-'):<9} "
            f"{fmt_age(replica.get('last_refresh_age_s')):>6}  "
            f"{replica.get('refresh_error') or ''}"
        )
    if not replicas:
        lines.append("(no replicas registered)")
    mixes = [
        (r["name"], r.get("tenants") or {}, r.get("cost_classes") or {})
        for r in replicas
    ]
    if any(t or c for _, t, c in mixes):
        lines.append("")
        lines.append("mix (per replica):")
        for name, tenants, costs in mixes:
            lines.append(
                f"  {name:<12} tenants: {fmt_mix(tenants):<32} "
                f"cost: {fmt_mix(costs)}"
            )
    quota = snap.get("quota") or {}
    tenants_ledger = quota.get("tenants") or {}
    if tenants_ledger:
        lines.append("")
        lines.append(
            f"quota leases (ttl={quota.get('ttl_s', 0):g}s "
            f"granted={quota.get('granted_total', 0)} "
            f"merged={quota.get('merged_total', 0)}):"
        )
        for tid in sorted(tenants_ledger):
            row = tenants_ledger[tid]
            lessees = row.get("lessees") or {}
            lessee_str = (
                " ".join(
                    f"{n}={lessees[n]:.1f}s" for n in sorted(lessees)
                )
                or "(none)"
            )
            lines.append(
                f"  {tid:<12} rps={row.get('rps', 0):g} "
                f"slice={row.get('slice_rps', 0):g}  lessees: {lessee_str}"
            )
    return "\n".join(lines)


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Fleet-router replica table (GET /v1/fleet/replicas)."
    )
    parser.add_argument("--url", default="http://localhost:50080")
    parser.add_argument(
        "--watch",
        type=float,
        default=None,
        metavar="SECONDS",
        help="refresh every N seconds until interrupted",
    )
    args = parser.parse_args()
    while True:
        try:
            response = httpx.get(f"{args.url}/v1/fleet/replicas", timeout=10.0)
            response.raise_for_status()
        except Exception as e:
            print(f"cannot reach router at {args.url}: {e}", file=sys.stderr)
            return 2
        # Best-effort: the replica table must render even when the
        # federated SLO surface is missing (older router) or slow.
        slo = None
        try:
            slo_response = httpx.get(f"{args.url}/v1/slo", timeout=10.0)
            if slo_response.status_code == 200:
                body = slo_response.json()
                slo = body if isinstance(body, dict) else None
        except Exception:
            pass
        if args.watch is not None:
            print("\033[2J\033[H", end="")  # clear like top
        print(render(response.json(), slo))
        if args.watch is None:
            return 0
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
