#!/usr/bin/env python
"""Validate the Pallas-kernel-under-shard_map path on real TPU silicon.

The model's mesh attention (models/transformer._attention) runs the flash
kernel INSIDE jax.shard_map on TPU — for tp-sharded heads, the flash-hop
ring over sp, and Ulysses. CI exercises this in interpreter mode only
(with check_vma=False; the vma checker cannot lower pallas interpreter
internals), so the Mosaic lowering of pallas_call under shard_map is
otherwise unproven on hardware. This script closes that: on the single
chip it builds a 1-device mesh and runs

1. the local flash kernel inside shard_map (the tp path's structure),
2. the flash-hop ring (1-hop degenerate ring: lax.ppermute + the causal
   kernel + lse merge machinery all lower),
2b. the Ulysses standalone entry (all_to_all + flash in one shard_map),
3. a tiny sharded transformer forward on the same mesh,

each checked against its unsharded reference. Exits 2 without a TPU,
nonzero on mismatch.
"""

from __future__ import annotations

import functools
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def run_measurements(emit) -> bool:
    """The full validation, inside an already-initialized jax process —
    callable from scripts/tpu-oneshot.py so one tunnel client captures the
    whole battery. Returns True iff every case matched its reference."""
    from bee_code_interpreter_tpu.ops.flash_attention import flash_attention
    from bee_code_interpreter_tpu.parallel.ring_attention import ring_attention

    mesh = Mesh(jax.devices()[:1], ("sp",))
    B, H, KVH, L, D = 2, 8, 2, 1024, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (B, H, L, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, KVH, L, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, KVH, L, D), jnp.bfloat16)
    spec4 = P(None, None, "sp", None)

    ref = flash_attention(q, k, v, True)  # kernel outside shard_map

    # 1. local flash inside shard_map (tp-path structure)
    fn_local = jax.shard_map(
        lambda q, k, v: flash_attention(q, k, v, True),
        mesh=mesh, in_specs=(spec4, spec4, spec4), out_specs=spec4,
        check_vma=False,
    )
    err_local = float(jnp.max(jnp.abs(
        (fn_local(q, k, v) - ref).astype(jnp.float32)
    )))

    # 2. flash-hop ring (ppermute + lse merge on silicon)
    fn_ring = jax.shard_map(
        functools.partial(ring_attention, axis_name="sp", use_flash=True),
        mesh=mesh, in_specs=(spec4, spec4, spec4), out_specs=spec4,
        check_vma=False,
    )
    err_ring = float(jnp.max(jnp.abs(
        (fn_ring(q, k, v) - ref).astype(jnp.float32)
    )))

    # 2a. windowed flash-hop ring: on the 1-device mesh only the own-block
    # hop runs, which is exactly the Pallas-specific part of the round-4
    # window-through-sp path — the kernel's window masking lowering under
    # shard_map. (The boundary-straddle hop is plain jax einsum math,
    # multi-hop geometry is pinned on the CPU mesh by tests/test_parallel.)
    fn_ring_win = jax.shard_map(
        functools.partial(
            ring_attention, axis_name="sp", use_flash=True, window=300
        ),
        mesh=mesh, in_specs=(spec4, spec4, spec4), out_specs=spec4,
        check_vma=False,
    )
    from bee_code_interpreter_tpu.parallel.ring_attention import (
        reference_attention,
    )

    ref_win = reference_attention(q, k, v, causal=True, window=300)
    err_ring_win = float(jnp.max(jnp.abs(
        (fn_ring_win(q, k, v) - ref_win).astype(jnp.float32)
    )))

    # 2b. Ulysses standalone entry (flash under shard_map via all_to_all —
    # the exact path ADVICE r3 flagged as never lowered on silicon)
    from bee_code_interpreter_tpu.parallel.ulysses import (
        ulysses_attention_sharded,
    )

    # Ulysses scatters heads over sp; KVH=2 divides sp=1 trivially here, the
    # lowering (all_to_all + pallas_call under one shard_map) is the point.
    out_uly = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    err_uly = float(jnp.max(jnp.abs((out_uly - ref).astype(jnp.float32))))

    # 3. sharded tiny transformer forward on the mesh vs mesh=None
    import dataclasses

    from bee_code_interpreter_tpu.models.transformer import (
        TransformerConfig, forward, init_params,
    )

    cfg = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
    params = init_params(cfg, jax.random.PRNGKey(3))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 64), 0, cfg.vocab_size)
    lg_mesh = forward(params, tokens, cfg, mesh)
    lg_none = forward(params, tokens, cfg, None)
    err_fwd = float(jnp.max(jnp.abs(lg_mesh - lg_none)))

    # 4. the paged-attention decode kernel's Mosaic lowering: scalar-
    # prefetched block-table index maps on silicon, vs the gather oracle
    import numpy as _np

    from bee_code_interpreter_tpu.ops.paged_attention import (
        paged_decode_attention,
    )

    kq = jax.random.normal(jax.random.PRNGKey(20), (3, 8, 128), jnp.bfloat16)
    kpool = jax.random.normal(
        jax.random.PRNGKey(21), (20, 2, 16, 128), jnp.bfloat16
    )
    vpool = jax.random.normal(
        jax.random.PRNGKey(22), (20, 2, 16, 128), jnp.bfloat16
    )
    ptable = jax.random.permutation(jax.random.PRNGKey(23), 20)[:12].reshape(
        3, 4
    ).astype(jnp.int32)
    lens = jnp.asarray([5, 33, 64], dtype=jnp.int32)
    got = paged_decode_attention(kq, kpool, vpool, ptable, lens)

    def gather_oracle():
        g = kpool[ptable].transpose(0, 2, 1, 3, 4).reshape(3, 2, 64, 128)
        gv = vpool[ptable].transpose(0, 2, 1, 3, 4).reshape(3, 2, 64, 128)
        qg = kq.reshape(3, 2, 4, 128).astype(jnp.float32)
        s = jnp.einsum("bgrd,bgsd->bgrs", qg, g.astype(jnp.float32))
        s = s / jnp.sqrt(128.0)
        mask = jnp.arange(64)[None, :] < lens[:, None]
        s = jnp.where(mask[:, None, None, :], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum(
            "bgrs,bgsd->bgrd", w, gv.astype(jnp.float32)
        ).reshape(3, 8, 128)

    err_paged = float(_np.max(_np.abs(
        _np.asarray(got, dtype=_np.float32) - _np.asarray(gather_oracle())
    )))

    ok = (err_local < 1e-2 and err_ring < 1e-2 and err_ring_win < 1e-2
          and err_uly < 1e-2 and err_fwd < 1e-2 and err_paged < 3e-2)
    payload = {
        "local_in_shardmap_err": round(err_local, 6),
        "flash_hop_ring_err": round(err_ring, 6),
        "windowed_ring_err": round(err_ring_win, 6),
        "ulysses_sharded_err": round(err_uly, 6),
        "sharded_forward_err": round(err_fwd, 6),
        "paged_attention_kernel_err": round(err_paged, 6),
        "ok": ok,
    }
    if ok:
        emit("shardmap_pallas_mosaic", payload)
    else:
        print(json.dumps({"case": "shardmap_pallas_mosaic", **payload}))
    return ok


def main() -> None:
    import functools
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    probe = bench.probe_tpu()
    if not probe.get("ok") or probe.get("platform") != "tpu":
        print(f"no TPU: {probe}", file=sys.stderr)
        sys.exit(2)

    from bee_code_interpreter_tpu.utils import evidence

    ok = run_measurements(
        functools.partial(
            evidence.emit, script="scripts/validate-shardmap-pallas.py"
        )
    )
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
