#!/usr/bin/env bash
# The one lint command CI needs (docs/analysis.md "Self-lint"): the
# asyncio self-lint, the await-aware concurrency lint, the accelerator-
# stack jaxlint, the cross-transport contractlint, and the metrics/docs
# convention lints. Exits nonzero on ANY unexplained finding (a stale
# suppression counts as one).
#
#   scripts/lint.sh            # human output
#   scripts/lint.sh --sarif    # SARIF 2.1.0 logs to lint-*.sarif
set -euo pipefail
cd "$(dirname "$0")/.."

PYTHON="${PYTHON:-python3}"

if [[ "${1:-}" == "--sarif" ]]; then
    "$PYTHON" scripts/analyze.py --self-lint --sarif > lint-asynclint.sarif
    "$PYTHON" scripts/analyze.py --concurrency-lint --sarif > lint-concurrency.sarif
    "$PYTHON" scripts/analyze.py --jax-lint --sarif > lint-jaxlint.sarif
    "$PYTHON" scripts/analyze.py --contract-lint --sarif > lint-contractlint.sarif
    echo "wrote lint-asynclint.sarif lint-concurrency.sarif lint-jaxlint.sarif lint-contractlint.sarif"
else
    echo "== asynclint (analysis/asynclint.py)"
    "$PYTHON" scripts/analyze.py --self-lint
    echo "== concurrencylint (analysis/concurrencylint.py)"
    "$PYTHON" scripts/analyze.py --concurrency-lint
    echo "== jaxlint (analysis/jaxlint.py)"
    "$PYTHON" scripts/analyze.py --jax-lint
    echo "== contractlint (analysis/contractlint.py)"
    "$PYTHON" scripts/analyze.py --contract-lint
fi

echo "== metrics/docs conventions (pytest)"
"$PYTHON" -m pytest -q \
    tests/test_asynclint.py \
    tests/test_concurrencylint.py \
    tests/test_jaxlint.py \
    tests/test_contractlint.py \
    tests/test_metrics_conventions.py
