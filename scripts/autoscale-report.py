#!/usr/bin/env python3
"""Render the pool autoscaler's decision log as a table (docs/autoscaling.md).

Fetches ``GET /v1/autoscale`` from a running service and prints the demand
snapshot, the forecast, and every retained scaling decision — the artifact
to read in ``advise`` mode before trusting the autoscaler with ``act``.

Exit codes:
  0  healthy (or nothing to report)
  1  service unreachable
  2  mode=act and the target is unmet past the forecast horizon — the
     autoscaler asked for capacity the pool could not deliver (spawn
     failures, breaker open, APP_AUTOSCALE_MAX vs quota): page-worthy.

    python scripts/autoscale-report.py [--url http://localhost:50081]
        [--limit N] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import httpx

UNMET_EXIT = 2


def fmt_ts(ts: float | None) -> str:
    if ts is None:
        return "-"
    return time.strftime("%H:%M:%S", time.localtime(ts))


def render(body: dict, limit: int) -> str:
    lines = []
    demand = body.get("demand") or {}
    forecast = body.get("forecast") or {}
    lines.append(
        f"demand: {demand.get('rps_10s', 0):.2f} rps (10s)"
        f"  peak={demand.get('peak_rps_60s', 0):g} rps"
        f"  warm_pop={demand.get('warm_pop_ratio_60s', 1.0):.0%}"
        f"  sheds(60s)={demand.get('sheds_60s', 0)}"
        f"  concurrency_hw={demand.get('concurrency_high_water_60s', 0)}"
    )
    lines.append(
        f"forecast: {forecast.get('forecast_rps', 0):.2f} rps"
        f" over a {forecast.get('horizon_s', 0):.1f}s horizon"
        f" (level={forecast.get('level_rps', 0):.2f}"
        f" trend={forecast.get('trend_rps_per_s', 0):+.2f}/s"
        f" peak={forecast.get('peak_rps', 0):g})"
    )
    if body.get("mode") is None:
        lines.append("autoscaler: (none — pool-less local backend)")
        return "\n".join(lines)
    lines.append(
        f"autoscaler: mode={body['mode']}"
        f"  pool {body.get('current_size', 0)}->{body.get('target', 0)}"
        f"  bounds=[{body.get('min', '?')}, {body.get('max', '?')}]"
        f"  decisions={body.get('decisions_total', 0)}"
    )
    decisions = (body.get("decisions") or [])[:limit]
    if not decisions:
        lines.append("(no scaling decisions retained)")
        return "\n".join(lines)
    lines.append("")
    header = (
        f"{'TIME':<9} {'ID':<8} {'DIR':<5} {'SIZE':<9} {'REASON':<10} "
        f"{'FORECAST':>9} {'DEMAND':>7} {'HORIZON':>8} {'APPLIED':>7}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for d in decisions:
        lines.append(
            f"{fmt_ts(d.get('ts')):<9} {d.get('decision_id', '-'):<8} "
            f"{d.get('direction', '-'):<5} "
            f"{str(d.get('from', '?')) + '->' + str(d.get('to', '?')):<9} "
            f"{d.get('reason', '-'):<10} "
            f"{d.get('forecast_rps', 0):>6.1f}rps "
            f"{d.get('demand_rps', 0):>4.1f}rps "
            f"{d.get('horizon_s', 0):>7.1f}s "
            f"{'yes' if d.get('applied') else 'no':>7}"
        )
    return "\n".join(lines)


def target_unmet_past_horizon(body: dict) -> bool:
    """True when mode=act asked for capacity the pool hasn't delivered one
    full forecast horizon after the deciding scale-up — the condition that
    means actuation is broken (quota, spawn failures, open breaker), not
    merely in progress."""
    if body.get("mode") != "act":
        return False
    target = body.get("target") or 0
    current = body.get("current_size") or 0
    if current >= target:
        return False
    last = body.get("last_decision")
    if not last or last.get("direction") != "up":
        return False
    # The DECIDING decision's horizon, not the current forecast's: spawn
    # samples arriving after the decision must neither suppress nor hasten
    # the page the decision itself promised.
    horizon = last.get("horizon_s", 0.0) or 0.0
    return time.time() - (last.get("ts") or 0.0) > horizon


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render GET /v1/autoscale's decision log as a table."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    parser.add_argument(
        "--limit", type=int, default=32,
        help="decisions to show, newest first (default 32)",
    )
    parser.add_argument(
        "--json", action="store_true", help="print the raw JSON body instead"
    )
    args = parser.parse_args()
    base = args.url.rstrip("/")
    try:
        with httpx.Client(timeout=10.0) as client:
            body = (
                client.get(f"{base}/v1/autoscale").raise_for_status().json()
            )
    except httpx.HTTPError as e:
        print(f"autoscale-report: cannot reach {base}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body, indent=2))
    else:
        print(render(body, max(0, args.limit)))
    if target_unmet_past_horizon(body):
        print(
            "autoscale-report: TARGET UNMET past the forecast horizon "
            f"(pool {body.get('current_size')}/{body.get('target')} in "
            "mode=act) — check spawn failures / breaker state / quota",
            file=sys.stderr,
        )
        return UNMET_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
