#!/usr/bin/env python3
"""Text rendering of the serving engine's deep-observability view
(docs/observability.md "Serving observability").

Fetches ``GET /v1/serving`` (+ optionally ``/v1/serving/requests``) from a
running service and prints a `top`-style dashboard — occupancy, page-pool
and fragmentation state, speculative accept rate, recent step cadence, and
a per-request table. ``--watch N`` refreshes every N seconds until
interrupted, like fleet-top.

    python scripts/serving-top.py [--url http://localhost:50081]
        [--requests N] [--steps N] [--watch SECONDS]
"""

from __future__ import annotations

import argparse
import sys
import time

import httpx


def fmt_ms(ms: float | None) -> str:
    if ms is None:
        return "-"
    if ms < 1000:
        return f"{ms:.1f}ms"
    return f"{ms / 1000:.2f}s"


def render_summary(snap: dict) -> str:
    lines = []
    if not snap.get("attached"):
        lines.append(
            "serving: monitor wired, no engine attached "
            "(ApplicationContext.attach_serving_engine)"
        )
        return "\n".join(lines)
    batcher = snap.get("batcher", {})
    totals = snap.get("totals", {})
    active = batcher.get("active_rows", 0)
    max_batch = batcher.get("max_batch", 0) or 1
    lines.append(
        f"serving: occupancy={active}/{batcher.get('max_batch', 0)}"
        f" ({active / max_batch:.0%})"
        f"  prefilling={batcher.get('prefilling_rows', 0)}"
        f"  queue_depth={snap.get('queue_depth', '-')}"
        f"  finished={totals.get('finished', 0)}"
        f"  rejected={totals.get('rejected', 0)}"
        f"  requeued={totals.get('requeued', 0)}"
        f"  preempted={totals.get('preempted', 0)}"
    )
    kv = snap.get("kv_cache", {})
    if kv:
        lines.append(
            f"kv-cache: pages free={kv.get('pages_free', 0)}"
            f" parked={kv.get('pages_parked', 0)}"
            f" held={kv.get('pages_held', 0)}"
            f" shared={kv.get('pages_shared', 0)}"
            f" /{kv.get('pages_total', 0)}"
            f"  fragmentation={kv.get('fragmentation', 0.0):.1%}"
        )
        prefix = kv.get("prefix", {})
        lines.append(
            "prefix-cache: "
            + (
                f"hit_ratio={prefix.get('hit_ratio', 0.0):.0%}"
                f" ({prefix.get('hits', 0)}/{prefix.get('lookups', 0)}"
                f" lookups, {prefix.get('pages_reused', 0)} pages reused,"
                f" {prefix.get('indexed_pages', 0)} indexed)"
                if prefix.get("enabled", True)
                else "disabled"
            )
        )
    spec = totals.get("spec_accepted", 0) + totals.get("spec_rejected", 0)
    if spec:
        lines.append(
            f"speculative: accept_rate={totals.get('spec_accept_ratio', 0.0):.0%}"
            f" ({totals.get('spec_accepted', 0)}/{spec} draft tokens)"
        )
    return "\n".join(lines)


def fmt_bytes(n: float | None) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return "-"


def render_accelerator(snap: dict | None) -> str:
    """The compile/HBM pane off ``GET /v1/accelerator``
    (docs/observability.md "Accelerator observability")."""
    if not snap:
        return ""
    compile_ = snap.get("compile", {})
    by_trigger = compile_.get("by_trigger", {})
    mesh = snap.get("mesh") or {}
    lines = [
        f"accelerator: mesh={mesh.get('shape', '1')}"
        f"  compiles={compile_.get('total', 0)}"
        f" (first_call={by_trigger.get('first_call', 0)},"
        f" retrace={by_trigger.get('retrace', 0)})"
    ]
    memory = snap.get("memory", {})
    for dev in memory.get("devices", []):
        est = " (estimated)" if dev.get("estimated") else ""
        lines.append(
            f"  hbm {dev.get('device', '-')}:"
            f" live={fmt_bytes(dev.get('live_bytes'))}"
            f" peak={fmt_bytes(dev.get('peak_bytes'))}"
            f" limit={fmt_bytes(dev.get('limit_bytes'))}{est}"
        )
    recent = compile_.get("recent", [])
    if recent:
        lines.append(
            f"  {'SEQ':>4} {'TRIGGER':<10} {'WALL':>8} "
            f"{'FUNCTION':<24} SIGNATURE"
        )
        for c in recent:
            lines.append(
                f"  {c.get('seq', 0):>4} {c.get('trigger', '-'):<10} "
                f"{fmt_ms(c.get('duration_ms')):>8} "
                f"{c.get('function', '-'):<24} {c.get('signature', '-')}"
            )
    return "\n".join(lines)


def render_steps(snap: dict) -> str:
    steps = snap.get("steps", {})
    last = steps.get("last", [])
    lines = [
        f"steps: {steps.get('recorded', 0)} recorded,"
        f" {steps.get('retained', 0)} retained"
    ]
    if not last:
        return lines[0]
    header = (
        f"  {'SEQ':>6} {'WALL':>8} {'ROWS':>4} {'PRE':>3} {'DEC':>4} "
        f"{'PTOK':>5} {'SPEC+':>5} {'SPEC-':>5} {'PG+':>4} {'PG-':>4} "
        f"{'FREE':>5}"
    )
    lines.append(header)
    for s in last:
        lines.append(
            f"  {s.get('seq', 0):>6} {fmt_ms(s.get('duration_ms')):>8} "
            f"{s.get('active_rows', 0):>4} {s.get('prefilling_rows', 0):>3} "
            f"{s.get('decode_tokens', 0):>4} {s.get('prefill_tokens', 0):>5} "
            f"{s.get('spec_accepted', 0):>5} {s.get('spec_rejected', 0):>5} "
            f"{s.get('pages_allocated', 0):>4} {s.get('pages_released', 0):>4} "
            f"{s.get('free_pages', 0):>5}"
        )
    return "\n".join(lines)


def render_requests(rows: list[dict]) -> str:
    lines = ["", f"requests (newest first, {len(rows)}):"]
    header = (
        f"  {'REQ':>5} {'STATE':<7} {'FINISH':<10} {'PTOK':>5} {'OTOK':>5} "
        f"{'PAGES':>5} {'PFX':>3} {'RQ':>2} {'TTFT':>8} {'WALL':>8}  TRACE"
    )
    lines.append(header)
    lines.append("  " + "-" * (len(header) - 2))
    for r in rows:
        lines.append(
            f"  {r.get('request_id', '-'):>5} "
            f"{'live' if r.get('active') else 'done':<7} "
            f"{(r.get('finish') or '-'):<10} "
            f"{r.get('prompt_tokens', 0):>5} {r.get('output_tokens', 0):>5} "
            f"{r.get('pages', 0):>5} {r.get('prefix_hit_pages', 0):>3} "
            f"{r.get('requeues', 0):>2} {fmt_ms(r.get('ttft_ms')):>8} "
            f"{fmt_ms(r.get('duration_ms')):>8}  {r.get('trace_id', '-')}"
        )
    if not rows:
        lines.append("  (no requests recorded)")
    return "\n".join(lines)


def render_once(
    client: httpx.Client, base: str, requests: int, steps: int
) -> None:
    resp = client.get(f"{base}/v1/serving", params={"steps": steps})
    if resp.status_code == 501:
        print("serving-top: no serving monitor wired into this server")
        return
    snap = resp.raise_for_status().json()
    print(render_summary(snap))
    if snap.get("attached"):
        print(render_steps(snap))
    # Compile/HBM pane: tolerate servers predating /v1/accelerator.
    accel_resp = client.get(
        f"{base}/v1/accelerator", params={"recent": min(steps, 8)}
    )
    if accel_resp.status_code == 200:
        pane = render_accelerator(accel_resp.json())
        if pane:
            print(pane)
    if requests > 0:
        rows = (
            client.get(
                f"{base}/v1/serving/requests", params={"limit": requests}
            )
            .raise_for_status()
            .json()["requests"]
        )
        print(render_requests(rows))


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render GET /v1/serving as a text dashboard."
    )
    parser.add_argument("--url", default="http://localhost:50081")
    parser.add_argument(
        "--requests",
        type=int,
        default=10,
        metavar="N",
        help="show the newest N per-request records (0 = none)",
    )
    parser.add_argument(
        "--steps",
        type=int,
        default=8,
        metavar="N",
        help="show the last N step records (0 = none)",
    )
    parser.add_argument(
        "--watch",
        type=float,
        default=0,
        metavar="SECONDS",
        help="refresh every N seconds until interrupted (0 = one shot)",
    )
    args = parser.parse_args()
    base = args.url.rstrip("/")
    try:
        with httpx.Client(timeout=10.0) as client:
            while True:
                try:
                    render_once(client, base, args.requests, args.steps)
                except httpx.HTTPError as e:
                    print(
                        f"serving-top: cannot reach {base}: {e}",
                        file=sys.stderr,
                    )
                    if args.watch <= 0:
                        return 1
                if args.watch <= 0:
                    return 0
                time.sleep(args.watch)
                print(f"\n--- {time.strftime('%H:%M:%S')} ---")
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
