# Continuous batching through the sandbox: requests of different lengths
# share one decode batch and one paged KV pool (models/serving.py over
# ops/paged_kv_cache.py). Three prompts are admitted as rows free up; each
# result must equal that prompt's solo greedy decode — batching other
# requests alongside cannot change an answer.
#
# f32 so the equality assert is trustworthy (same reasoning as
# speculative-decode.py: bf16 near-tie argmax flips are rounding noise).
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.serving import ContinuousBatcher

on_tpu = jax.devices()[0].platform == "tpu"
config = dataclasses.replace(
    T.TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
        n_kv_heads=4, max_seq_len=2048,
    ) if on_tpu else T.TransformerConfig.tiny(),
    dtype=jnp.float32,
)
params = T.init_params(config, jax.random.PRNGKey(0))
model = T.Transformer(config)

lengths = [5, 11, 8]
new_tokens = 12
prompts = [
    np.asarray(jax.random.randint(jax.random.PRNGKey(i + 1), (L,), 0,
                                  config.vocab_size))
    for i, L in enumerate(lengths)
]
solo = [
    np.asarray(model.generate_cached(
        params, jnp.asarray(p)[None, :], max_new_tokens=new_tokens
    )[0, len(p):]).tolist()
    for p in prompts
]

batcher = ContinuousBatcher(
    params, config, max_batch=2, n_pages=32, page_size=8,
    max_pages_per_seq=4,
)
t0 = time.time()
pending = list(enumerate(prompts))
requests: dict[int, int] = {}
steps = 0
while pending or any(not batcher.is_done(r) for r in requests.values()):
    while pending and batcher.has_free_row():
        idx, prompt = pending[0]
        try:
            requests[idx] = batcher.submit(prompt, new_tokens)
        except RuntimeError:
            break  # pages exhausted: decode until some free
        pending.pop(0)
    batcher.step()
    steps += 1

for idx in range(len(prompts)):
    got = batcher.result(requests[idx])
    assert got == solo[idx], (idx, got, solo[idx])
print(f"continuous batching OK: {len(prompts)} requests over max_batch=2, "
      f"{steps} steps, {time.time() - t0:.1f}s, outputs == solo decode")

# --- speculative mode: a small draft proposes, each row commits its OWN
# accept length per round (no lockstep minimum across the batch) — output
# still exactly equals the solo greedy decode.
draft_config = dataclasses.replace(
    config, n_layers=1, d_model=32, n_heads=2, n_kv_heads=2, d_ff=64,
)
draft_params = T.init_params(draft_config, jax.random.PRNGKey(9))
spec = ContinuousBatcher(
    params, config, max_batch=2, n_pages=32, page_size=8,
    max_pages_per_seq=4, draft_params=draft_params,
    draft_config=draft_config, gamma=3,
)
reqs = [spec.submit(p, new_tokens) for p in prompts[:2]]
rounds = 0
while not all(spec.is_done(r) for r in reqs):
    spec.step()
    rounds += 1
for i, r in enumerate(reqs):
    assert spec.result(r) == solo[i], (i, spec.result(r), solo[i])
print(f"speculative serving OK: {len(reqs)} requests, {rounds} rounds for "
      f"{new_tokens} tokens each (gamma=3), outputs == solo decode")

# --- prefix caching: a repeat prompt hits the page index and admits via a
# suffix-only prefill — shared pages are reused (refcounted, kept past
# retirement), and the greedy output is exactly the solo decode still.
pc = ContinuousBatcher(
    params, config, max_batch=2, n_pages=32, page_size=8,
    max_pages_per_seq=4, prefix_cache=True,
)
r1 = pc.submit(prompts[1], new_tokens)
pc.run_to_completion()
r2 = pc.submit(prompts[1], new_tokens)
pc.run_to_completion()
assert pc.result(r1) == pc.result(r2) == solo[1]
s = pc.prefix_stats
print(f"prefix caching OK: repeat prompt hits={s['hits']} pages_reused="
      f"{s['pages_reused']}, outputs == solo decode")

# --- dp × tp serving: two engine replicas, each tensor-parallel over its
# own pair of devices, behind one router — the standard serving topology,
# exercised right here on the virtual device mesh.
import jax
import numpy as np
from jax.sharding import Mesh

from bee_code_interpreter_tpu.models.replicated import ReplicatedEngine

if len(jax.devices()) >= 4:
    meshes = [
        Mesh(np.array(jax.devices()[0:2]), ("tp",)),
        Mesh(np.array(jax.devices()[2:4]), ("tp",)),
    ]
    rep = ReplicatedEngine.build(
        params, config, 2, meshes=meshes,
        max_batch=2, n_pages=32, page_size=8, max_pages_per_seq=4,
    )
    rtix = [rep.submit(p, new_tokens) for p in prompts]
    rep.run_to_completion()
    for i, t in enumerate(rtix):
        assert rep.result(t) == solo[i], (i, rep.result(t), solo[i])
    replicas_used = {rep.replica_of(t) for t in rtix}
    print(f"dp x tp serving OK: {len(rtix)} requests over 2 replicas x tp=2 "
          f"(replicas used: {sorted(replicas_used)}), outputs == solo decode")
else:
    print("dp x tp serving SKIPPED: needs >= 4 devices")
