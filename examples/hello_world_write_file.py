# Workspace write: the created file is picked up by the executor's
# changed-file scan and snapshotted into storage, so a follow-up execution
# (hello_world_read_file.py) can restore and read it. Parity payload for the
# reference's examples/hello_world_write_file.py.

from pathlib import Path

Path("example.txt").write_text("Hello, world! How are you?")
