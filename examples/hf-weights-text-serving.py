# Real-weights serving end-to-end: load a HuggingFace Llama checkpoint
# (models/hf_loader.py — logits parity with transformers pinned at 1e-4),
# wrap it in the paged continuous batcher + engine queue, and serve TEXT
# with stop strings and streaming (models/text.py).
#
# Offline-hermetic: the "checkpoint" is a tiny randomly-initialized HF
# LlamaForCausalLM and the tokenizer is a char-level stand-in satisfying
# the encode/decode protocol — swap in from_pretrained(...) and an HF
# tokenizer for real weights; every line below stays the same.
import numpy as np
import torch
import transformers

import jax.numpy as jnp

from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.hf_loader import load_llama_params
from bee_code_interpreter_tpu.models.serving import ContinuousBatcher
from bee_code_interpreter_tpu.models.text import TextEngine

hf_config = transformers.LlamaConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=128, rms_norm_eps=1e-5,
    attention_bias=False, tie_word_embeddings=False,
)
torch.manual_seed(0)
hf_model = transformers.LlamaForCausalLM(hf_config).eval()

params, config = load_llama_params(hf_model, dtype=jnp.float32)

# parity spot-check: the loaded weights ARE the HF model
tokens = np.array([[5, 3, 7, 2, 9, 4, 1, 8]], dtype=np.int32)
with torch.no_grad():
    hf_logits = hf_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
from bee_code_interpreter_tpu.models.transformer import forward

ours = np.asarray(forward(params, jnp.asarray(tokens), config))
err = float(np.max(np.abs(ours - hf_logits)))
assert err < 1e-3, err
print(f"hf parity OK: max logits err {err:.2e} vs transformers forward")


class CharTokenizer:  # stand-in satisfying the TextEngine protocol
    def encode(self, text):
        return [ord(ch) % config.vocab_size for ch in text]

    def decode(self, toks):
        return "".join(chr(32 + (t % 94)) for t in toks)


te = TextEngine(
    Engine(ContinuousBatcher(params, config, max_batch=2, n_pages=32,
                             page_size=4, max_pages_per_seq=8)),
    CharTokenizer(),
)

# serve two text requests together, stream one of them
t_a = te.submit("hello tpu", 10)
t_b = te.submit("serving!", 8)
chunks = []
while not (te.is_done(t_a) and te.is_done(t_b)):
    te.step()
    chunk = te.new_text(t_a)
    if chunk:
        chunks.append(chunk)
chunks.append(te.new_text(t_a))
assert "".join(chunks) == te.text(t_a)
assert len(te.text(t_b)) == 8
print(f"text serving OK: streamed {len([c for c in chunks if c])} chunks; "
      f"batch-mate finished reason={te.finish_reason(t_b)}")

# stop strings: truncate at a substring of the greedy completion
full = te.text(t_a)
t_c = te.submit("hello tpu", 10, stop=(full[4:6],))
te.run_to_completion()
assert te.text(t_c) == full[: full.find(full[4:6])]
assert te.finish_reason(t_c) == "stop"
print("stop strings OK: completion truncated at the stop, "
      "request cancelled to free pages")
