# On-the-fly dependency install probe (parity with reference
# examples/cowsay.py): cowsay is not preinstalled; the executor's import
# guesser should pip-install it before running this.
import cowsay

cowsay.cow("mooooo from the TPU sandbox")
