"""BASELINE.json config #2: torch ResNet-50 inference through /v1/execute.

Submit this file's source as the ``source_code`` of a ``POST /v1/execute``.
Inside the TPU sandbox image the runtime shim (runtime/shim/sitecustomize.py)
sets torch's default device to "xla" when torch_xla is importable, so the
model and inputs land on the pod's TPU chip without the payload mentioning
XLA at all — the same transparent-acceleration contract as the numpy reroute.
On a CPU-only sandbox the exact same payload runs on host torch.

(The reference ships torch CPU wheels in its executor image and this payload
shape in its BASELINE configs; torchvision is auto-installed by the dep
guesser on first use.)
"""

import time

import torch
import torchvision

model = torchvision.models.resnet50(weights=None).eval()
device = next(model.parameters()).device  # "xla:0" on TPU sandboxes
batch = torch.randn(8, 3, 224, 224, device=device)

with torch.no_grad():
    model(batch)  # warm (first XLA compile happens here)
    t0 = time.time()
    for _ in range(8):
        out = model(batch)
    if device.type == "xla":
        import torch_xla.core.xla_model as xm

        xm.mark_step()  # flush the lazy graph before reading the clock
    dt = time.time() - t0

print(f"device={device} top1={int(out.argmax(1)[0])}")
print(f"RESULT_IMAGES_PER_S {8 * 8 / dt:.1f}")
