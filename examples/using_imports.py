# Preinstalled scientific stack probe (parity with reference
# examples/using_imports.py): numpy/pandas/scipy interop, with the numpy work
# transparently rerouted to the TPU where it is large enough.
import numpy as np
import pandas as pd
from scipy import stats

a = np.random.rand(2_000_000)
b = np.random.rand(2_000_000)
t, p = stats.ttest_ind(np.asarray(a), np.asarray(b))  # scipy consumes host views
df = pd.DataFrame({"t": [t], "p": [p]})
print(df.to_string(index=False))
