# Workspace environment probe (parity with reference examples/ls.py).
import os
import sys

print("cwd:", os.getcwd())
print("python:", sys.version.split()[0])
print("entries:", sorted(os.listdir(".")))
