# Dense-array benchmark payload (capability parity with the reference's
# examples/benchmark-numpy.py:19-29): plain numpy code, self-timed. Under the
# TPU sandbox runtime the creation + square + sum chain runs on the attached
# chip via the transparent XLA reroute; on the reference it runs on host CPU.
import time

import numpy as np

n = 10**8
start = time.time()
x = np.random.rand(n)
y = np.square(x)
result = float(np.sum(y))
elapsed = time.time() - start
print(f"kind={type(y).__name__}")
print(f"sum(square(rand({n}))) = {result:.1f} in {elapsed:.3f}s")
