# Speculative decoding through the sandbox: a 1-layer draft proposes, the
# target verifies a whole window per forward — output is EXACTLY the
# target's greedy decode (the draft only changes how many target forwards
# run). Uses the bundled models/speculative.py.
#
# f32 everywhere: the equality check compares the window forward against
# single-step decode, whose logits agree only up to rounding — at bf16 a
# near-tied argmax can flip, which is rounding noise, not a speculation
# bug. f32 margins dwarf that rounding, making the assert trustworthy.
import dataclasses
import time

import jax

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models import speculative_generate

on_tpu = jax.devices()[0].platform == "tpu"
config = dataclasses.replace(
    T.TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
        n_kv_heads=4, max_seq_len=2048,
    ) if on_tpu else T.TransformerConfig.tiny(),
    dtype=jax.numpy.float32,
)
draft_config = dataclasses.replace(config, n_layers=1)

params = T.init_params(config, jax.random.PRNGKey(0))
draft_params = T.init_params(draft_config, jax.random.PRNGKey(1))
prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 16), 0, config.vocab_size)
n_new = 32 if on_tpu else 8

def run():
    return speculative_generate(
        params, config, draft_params, draft_config, prompt,
        max_new_tokens=n_new, gamma=4,
    )

spec = run()  # warm: trace + compile happens here, not in the timed call
jax.block_until_ready(spec)
t0 = time.time()
spec = run()
jax.block_until_ready(spec)
spec_s = time.time() - t0

greedy = T.Transformer(config).generate_cached(params, prompt, max_new_tokens=n_new)
exact = bool((spec == greedy).all())
print(f"speculative decode: {n_new} tokens in {spec_s:.2f}s, "
      f"exact-vs-greedy {exact}")
assert exact
