"""BASELINE.json config #4: HuggingFace BERT-base inference via /v1/execute.

Submit as the ``source_code`` of a ``POST /v1/execute``. transformers is
preinstalled in the sandbox image; the model weights download on first use
(cached under the workspace, so a warm pool with a shared cache volume pays
it once). On a TPU sandbox, torch lands on "xla" via the runtime shim; the
flax path below is the jax-native route and needs no shim at all.
"""

import time

from transformers import AutoTokenizer, FlaxBertModel

tokenizer = AutoTokenizer.from_pretrained("bert-base-uncased")
model = FlaxBertModel.from_pretrained("bert-base-uncased")

texts = ["The TPU sandbox runs %d payloads." % i for i in range(32)]
batch = tokenizer(texts, return_tensors="np", padding="max_length", max_length=128)

model(**batch)  # warm: first call compiles under jit
t0 = time.time()
for _ in range(8):
    out = model(**batch)
out.last_hidden_state.block_until_ready()
dt = time.time() - t0

print(f"hidden={out.last_hidden_state.shape}")
print(f"RESULT_SEQS_PER_S {32 * 8 / dt:.1f}")
