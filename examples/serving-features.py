# The request-level serving surface of the continuous batcher
# (models/serving.py) in one runnable tour: stop sequences + finish
# reasons, per-token logprobs, logit_bias, the allowed_tokens grammar
# hook, request cancellation, and multi-LoRA serving (per-request
# adapters in one compiled batch).
#
# f32 so the equality asserts are trustworthy (same reasoning as
# speculative-decode.py: bf16 near-tie argmax flips are rounding noise).
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.lora import init_lora, merge_lora
from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)

config = dataclasses.replace(
    T.TransformerConfig.tiny(), n_kv_heads=2, dtype=jnp.float32,
)
params = T.init_params(config, jax.random.PRNGKey(0))
model = T.Transformer(config)
prompt = [5, 3, 7, 2, 9, 4, 1, 8]


def solo(p, n):
    out = model.generate_cached(
        p, jnp.asarray(prompt, dtype=jnp.int32)[None, :], max_new_tokens=n
    )
    return np.asarray(out[0, len(prompt):]).tolist()


def batcher(**kw):
    return ContinuousBatcher(
        params, config, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, **kw,
    )


# --- stop sequences, finish reasons, logprobs ---------------------------
want = solo(params, 8)
b = batcher()
r = b.submit(prompt, 8, sampling=SamplingParams(
    stop_sequences=((want[3], want[4]),), logprobs=True))
b.run_to_completion()
assert b.result(r) == want[:3]          # matched stop trimmed
assert b.finish_reason(r) == "stop"
lps = b.result_logprobs(r)
assert len(lps) == 3 and all(lp <= 0.0 for lp in lps)
print(f"stops+logprobs OK: trimmed at the stop sequence, "
      f"finish={b.finish_reason(r)}, logprobs={[round(x, 2) for x in lps]}")

# --- constrained decoding: a two-state grammar + a forced token ---------
A, B_tok = 9, 17


def alternate(generated):
    if not generated:
        return [A]
    return [B_tok] if generated[-1] == A else [A]


b = batcher()
r_grammar = b.submit(prompt, 6, sampling=SamplingParams(
    allowed_tokens=alternate))
r_forced = b.submit(prompt, 3, sampling=SamplingParams(
    logit_bias={7: 1e9}))
b.run_to_completion()
assert b.result(r_grammar) == [A, B_tok, A, B_tok, A, B_tok]
assert b.result(r_forced) == [7, 7, 7]
print("constrained decoding OK: grammar hook drove A/B alternation, "
      "logit_bias forced a token")

# --- cancellation -------------------------------------------------------
b = batcher()
r_cancel = b.submit(prompt, 20)
b.step()
b.cancel(r_cancel)
assert b.finish_reason(r_cancel) == "cancelled"
assert len(b.result(r_cancel)) == 2  # first token + one step, kept
print("cancel OK: pages freed mid-decode, partial output kept")

# --- multi-LoRA: two adapters and the base in ONE batch -----------------
def adapter(seed):
    lora = init_lora(config, jax.random.PRNGKey(seed), rank=4)
    return {t: {"A": ab["A"],
                "B": jax.random.normal(jax.random.PRNGKey(seed + 50),
                                       ab["B"].shape, jnp.float32) * 0.3}
            for t, ab in lora.items()}


adapters = [adapter(1), adapter(2)]
mb = ContinuousBatcher(
    params, config, max_batch=3, n_pages=40, page_size=4,
    max_pages_per_seq=8, adapters=adapters, lora_scale=2.0,
)
r0 = mb.submit(prompt, 5, adapter=0)
r1 = mb.submit(prompt, 5, adapter=1)
rb = mb.submit(prompt, 5)
mb.run_to_completion()
assert mb.result(r0) == solo(merge_lora(params, adapters[0], 2.0), 5)
assert mb.result(r1) == solo(merge_lora(params, adapters[1], 2.0), 5)
assert mb.result(rb) == solo(params, 5)
print("multi-LoRA OK: 2 adapters + base served in one batch, each equal "
      "to its merged-params solo decode")

# --- interleaved admission + snapshot/resume ----------------------------
# A long prompt admits one window per step while a short request keeps
# decoding; mid-way through, the whole serving state snapshots, and a
# fresh batcher resumes it to the same tokens.
import pickle

long_prompt = [int(x) for x in np.random.default_rng(3).integers(
    0, config.vocab_size, 21)]
ib = ContinuousBatcher(
    params, config, max_batch=2, n_pages=40, page_size=4,
    max_pages_per_seq=8,
)
r_short = ib.submit(prompt, 8)
r_long = ib.submit(long_prompt, 4, interleave_admission=4)
interleave_steps = 0
while ib.prefill_state:
    ib.step()
    interleave_steps += 1
snap = pickle.dumps(ib.state_dict())
resumed = ContinuousBatcher(
    params, config, max_batch=2, n_pages=40, page_size=4,
    max_pages_per_seq=8,
)
resumed.load_state_dict(pickle.loads(snap))
resumed.run_to_completion()
long_ref = model.generate_cached(
    params, jnp.asarray(long_prompt, dtype=jnp.int32)[None, :],
    max_new_tokens=4,
)
assert resumed.result(r_long) == np.asarray(
    long_ref[0, len(long_prompt):]).tolist()
assert resumed.result(r_short) == want  # the solo decode from the top
print(f"interleaved admission OK: {interleave_steps} windows while the "
      "short request kept decoding; snapshot resumed on a fresh batcher, "
      "outputs == solo decode")
