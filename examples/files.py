# Workspace file round-trip probe (parity with reference examples/files.py and
# hello_world_{read,write}_file.py): files written here come back in the
# response's file map and can be re-mounted into the next execution.
from pathlib import Path

Path("notes/session.txt").parent.mkdir(parents=True, exist_ok=True)
Path("notes/session.txt").write_text("state carried between executions\n")
print(sorted(str(p) for p in Path(".").rglob("*") if p.is_file()))
