# Long-context demo: exact attention over a sequence sharded across all
# devices with K/V rotating on the ICI ring (parallel/ring_attention.py).
import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.parallel import make_mesh
from bee_code_interpreter_tpu.parallel.ring_attention import ring_attention_sharded

n = len(jax.devices())
mesh = make_mesh({"sp": n})
B, H, L, D = 1, 8, 1024 * n, 128  # L/n per device — scales with the ring
q, k, v = (
    jax.random.normal(jax.random.PRNGKey(i), (B, H, L, D), dtype=jnp.bfloat16)
    for i in range(3)
)
out = ring_attention_sharded(mesh, q, k, v, causal=True)
print(f"ring attention over {n} device(s): out {out.shape} {out.dtype}")
print(f"finite: {bool(jnp.isfinite(out.astype(jnp.float32)).all())}")
