# Long-context demo: exact attention over a sequence sharded across all
# devices with K/V rotating on the ICI ring (parallel/ring_attention.py).
# On TPU each hop runs the Pallas flash kernel (hops merge on their
# log-sum-exp); grouped-query K/V stays compact, so the ring moves
# KVH/H of the bytes a broadcast layout would.
import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.parallel import make_mesh
from bee_code_interpreter_tpu.parallel.ring_attention import ring_attention_sharded

n = len(jax.devices())
mesh = make_mesh({"sp": n})
B, H, KVH, L, D = 1, 8, 2, 1024 * n, 128  # L/n per device; compact GQA K/V
q = jax.random.normal(jax.random.PRNGKey(0), (B, H, L, D), dtype=jnp.bfloat16)
k, v = (
    jax.random.normal(jax.random.PRNGKey(i), (B, KVH, L, D), dtype=jnp.bfloat16)
    for i in (1, 2)
)
out = ring_attention_sharded(mesh, q, k, v, causal=True)
print(f"ring attention over {n} device(s): out {out.shape} {out.dtype}")
print(f"finite: {bool(jnp.isfinite(out.astype(jnp.float32)).all())}")

# Sliding-window variant: hops entirely below the window are skipped like
# future blocks, so a window spanning w/L_local blocks attends O(w/L_local)
# of the sp hops instead of all of them — the long-context win compounds
# with Mistral-style local attention.
w = L // max(2, n)
out_w = ring_attention_sharded(mesh, q, k, v, causal=True, window=w)
print(f"windowed (w={w}): out {out_w.shape}, "
      f"finite: {bool(jnp.isfinite(out_w.astype(jnp.float32)).all())}")
