# Native-JAX ResNet training through the sandbox — the framework-side
# counterpart to resnet50-torch-xla.py (which drives torch-xla). Uses the
# bundled models/vision.py family: NHWC, bf16 convs on the MXU, GroupNorm
# (no cross-device batch-stat sync), data-parallel over every local device.
import time

import jax
import jax.numpy as jnp
import optax

from bee_code_interpreter_tpu.models.vision import ResNet, ResNetConfig
from bee_code_interpreter_tpu.parallel import make_mesh

n_dev = len(jax.devices())
mesh = make_mesh({"dp": n_dev})
config = ResNetConfig.resnet50() if jax.devices()[0].platform == "tpu" else (
    ResNetConfig.tiny()
)
model = ResNet(config, mesh)
params = model.init(jax.random.PRNGKey(0))

optimizer = optax.sgd(0.1, momentum=0.9)
opt_state = optimizer.init(params)
step = model.make_train_step(optimizer)

B = 8 * n_dev
size = 224 if jax.devices()[0].platform == "tpu" else 32
batch = {
    "images": jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (B, size, size, 3)),
        model.batch_sharding(),
    ),
    "labels": jax.device_put(
        jax.random.randint(jax.random.PRNGKey(2), (B,), 0, config.num_classes),
        model.batch_sharding(),
    ),
}

params, opt_state, loss = step(params, opt_state, batch)  # compile + step 0
t0 = time.time()
steps = 5
for _ in range(steps):
    params, opt_state, loss = step(params, opt_state, batch)
dt = time.time() - t0
print(f"resnet train: {steps} steps of batch {B} in {dt:.2f}s "
      f"({steps * B / dt:.1f} img/s), loss {float(loss):.4f}")
