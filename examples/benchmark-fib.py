# CPU benchmark payload (parity with reference examples/benchmark-fib.py:17-33):
# pure-Python bignum work, deliberately NOT acceleratable — measures the
# sandbox's plain interpreter throughput.
import time


def fib(n: int) -> int:
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


start = time.time()
for _ in range(1000):
    fib(10000)
print(f"1000 x fib(10000) in {time.time() - start:.3f}s")
