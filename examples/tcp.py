# Network egress probe (parity with reference examples/tcp.py): reports
# whether the sandbox allows outbound TCP — deployments typically restrict it
# with NetworkPolicy.
import socket

try:
    with socket.create_connection(("1.1.1.1", 443), timeout=3):
        print("egress: OPEN")
except OSError as e:
    print(f"egress: BLOCKED ({e})")
