# Workspace read: expects example.txt restored from storage via the request's
# {path -> id} file map (written by hello_world_write_file.py in a previous
# execution). Parity payload for the reference's examples/hello_world_read_file.py.

from pathlib import Path

print(Path("example.txt").read_text())
