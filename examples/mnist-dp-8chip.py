# The BASELINE.json "JAX MNIST training across 8 chips" config: data-parallel
# training using the bundled model library inside the sandbox.
import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.models import MnistMlp
from bee_code_interpreter_tpu.parallel import make_mesh

n = len(jax.devices())
mesh = make_mesh({"dp": n})
model = MnistMlp(mesh=mesh)
params = model.init(jax.random.PRNGKey(0))
step, optimizer = model.make_train_step(0.05)
opt_state = optimizer.init(params)

key = jax.random.PRNGKey(1)
batch = jax.device_put(
    {
        "image": jax.random.normal(key, (64 * n, 784)),
        "label": jax.random.randint(key, (64 * n,), 0, 10),
    },
    model.batch_sharding(),
)
for i in range(20):
    params, opt_state, loss = step(params, opt_state, batch)
    if i % 5 == 0:
        print(f"step {i}: loss {float(loss):.4f}")
print(f"trained data-parallel over {n} device(s): {jax.devices()}")
