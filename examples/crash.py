# Nonzero-exit probe (parity with reference examples/crash.py): the service
# must surface the exit code and traceback, not 500.
raise RuntimeError("intentional crash to exercise error propagation")
