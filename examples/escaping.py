# Quoting/escaping edge cases (parity with reference examples/escaping.py —
# which probed xonsh quirks; we run plain python so these must all be literal).
print("double \" and single ' quotes")
print('backslash \\ and tab \t end')
print("""triple ' " mixed $HOME `backticks` $(subshell)""")
print("unicode: ünïcödé ✓ 中文")
