# Training-state checkpoint/resume through the sandbox: save a sharded
# train state under /workspace (so it rides the service's file
# snapshot/restore between executions — pass the returned file map back in
# the next request and training continues where it stopped), then restore
# it and verify the resumed state matches.
import jax
import jax.numpy as jnp
import optax

from bee_code_interpreter_tpu.models.transformer import Transformer, TransformerConfig
from bee_code_interpreter_tpu.utils.checkpoint import TrainCheckpointer, abstract_like

config = TransformerConfig.tiny()
model = Transformer(config)
params = model.init(jax.random.PRNGKey(0))
optimizer = model.make_optimizer(1e-3)
opt_state = optimizer.init(params)
step = model.make_train_step(optimizer)

tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, config.vocab_size)
batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}

for i in range(3):
    params, opt_state, loss = step(params, opt_state, batch)

state = {"params": params, "opt_state": opt_state, "step": jnp.int32(3)}
with TrainCheckpointer("ckpt") as ckpt:
    ckpt.save(3, state)
    resumed = ckpt.restore(template=abstract_like(state))

same = all(
    bool(jnp.array_equal(a, b))
    for a, b in zip(jax.tree.leaves(resumed), jax.tree.leaves(state))
)
print(f"checkpoint resume: step {int(resumed['step'])}, "
      f"loss {float(loss):.4f}, state-exact {same}")
