# Pure-CPU recursive workload: exercises the sandbox's plain-python path
# (no TPU, no imports). Parity payload for the reference's examples/fib.py,
# capped at 35 terms so the naive recursion stays well inside the sandbox's
# 60 s execution timeout (heavier CPU burn lives in benchmark-fib.py).

def fib(n: int) -> int:
    return n if n < 2 else fib(n - 1) + fib(n - 2)


for i in range(35):
    print(fib(i))
