# The headline TPU payload (bench.py runs this shape through /v1/execute):
# a jit-compiled bf16 matmul chain — the MXU at work from LLM-submitted code.
import time

import jax
import jax.numpy as jnp
from jax import lax

n, iters = 8192, 60
a = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype=jnp.bfloat16)


@jax.jit
def chain(a):
    def body(i, x):
        return (a @ x) * jnp.bfloat16(0.001)
    return lax.fori_loop(0, iters, body, a).sum()


float(chain(a))  # compile
t0 = time.time()
float(chain(a))
dt = time.time() - t0
print(f"devices: {jax.devices()}")
print(f"{2 * n**3 * iters / dt / 1e12:.1f} TFLOPS")
